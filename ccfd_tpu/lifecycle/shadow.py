"""Shadow scoring: the challenger sees live traffic off the critical path.

``wrap()`` interposes on the router's score lane (for the parallel router
the wrap sits UNDER the coalescing :class:`~ccfd_tpu.serving.batcher.
DynamicBatcher`, so the tap observes the same coalesced batches the device
scores). The hot-path cost is one flag read when no challenger is armed and
one bounded-deque append when one is: the challenger's own forward runs on
the tap's worker thread against the scorer's double-buffered challenger
slot (:meth:`ccfd_tpu.serving.scorer.Scorer.challenger_score` — a host
numpy forward, so shadow scoring never contends for the device).

Each drained batch produces ONE paired record onto the shadow topic::

    {"version": <challenger id>, "champion": [...], "challenger": [...]}

which the evaluator folds into score-distribution histograms (PSI) and
alert-rate deltas. Shadow evaluation is a SAMPLE by design, bounded two
ways so the live pipeline never pays for it: a token-bucket row budget
(``max_rows_per_s``; on a saturated host the worker thread's numpy
forwards and pair production would otherwise steal cores from the routing
loop — bench.py's ``pipeline.shadow`` row is the acceptance number) and a
bounded queue (challenger slower than the admitted stream). Batches past
either bound drop OLDEST-first, counted in
``ccfd_lifecycle_shadow_dropped_total`` — the evaluator's verdict just
accumulates over a slightly longer window.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable

import numpy as np


class ShadowTap:
    def __init__(
        self,
        scorer: Any,
        broker: Any,
        topic: str,
        registry: Any = None,
        max_queued_batches: int = 64,
        max_rows_per_s: float = 2048.0,
        max_queued_rows: int = 8192,
    ):
        self.scorer = scorer
        self.broker = broker
        self.topic = topic
        self.max_queued_batches = int(max_queued_batches)
        # row-denominated queue bound: the seq lane offers full (B, L, F)
        # history batches (~L x the row lane's bytes per row-batch), so a
        # batch-count bound alone would admit gigabytes of resident
        # tapped state behind a slow challenger; oldest batches drop
        # first past either bound
        self.max_queued_rows = int(max_queued_rows)
        self._queued_rows = 0
        # sampling budget: rows/s admitted into the shadow queue. Deficit
        # token bucket — a batch is admitted whenever the balance is
        # positive and then charged in full, so batches BIGGER than one
        # second's budget still sample through (at a proportionally lower
        # batch rate) instead of starving. 0 = unlimited.
        self.max_rows_per_s = float(max_rows_per_s)
        self._tokens = self.max_rows_per_s
        self._t_refill = time.monotonic()
        # hot-path gate: plain attribute read (GIL-atomic), no lock
        self._armed_version: int | None = None
        self._mu = threading.Lock()
        self._queue: deque[tuple[int, np.ndarray, np.ndarray]] = deque()
        self._stop = threading.Event()
        self._c_batches = self._c_rows = self._c_dropped = None
        self._c_pairs = self._c_errors = None
        if registry is not None:
            self._c_batches = registry.counter(
                "ccfd_lifecycle_shadow_batches_total",
                "live batches tapped for challenger shadow scoring",
            )
            self._c_rows = registry.counter(
                "ccfd_lifecycle_shadow_rows_total",
                "rows shadow-scored by the challenger",
            )
            self._c_dropped = registry.counter(
                "ccfd_lifecycle_shadow_dropped_total",
                "tapped ROWS dropped by the sampling budget or a full "
                "shadow queue (same unit as shadow_rows_total, so the "
                "board's scored-vs-dropped panel compares like for like; "
                "the hot path never blocks on shadow scoring)",
            )
            self._c_pairs = registry.counter(
                "ccfd_lifecycle_shadow_pairs_produced_total",
                "paired champion/challenger score records produced to the "
                "shadow topic",
            )
            self._c_errors = registry.counter(
                "ccfd_lifecycle_shadow_errors_total",
                "challenger shadow-score failures (batch skipped)",
            )

    # -- hot path ----------------------------------------------------------
    def wrap(self, score_fn: Callable[[np.ndarray], np.ndarray]) -> Callable:
        """Interpose on the champion score lane. The returned callable is
        what the router (or the parallel router's coalescing batcher)
        dispatches; with no challenger armed it adds one attribute read."""

        def tapped(x: np.ndarray) -> np.ndarray:
            proba = score_fn(x)
            version = self._armed_version
            if version is not None:
                self._offer(version, x, proba)
            return proba

        tapped.__wrapped__ = score_fn  # introspection/debugging
        return tapped

    def offer(self, x: np.ndarray, proba: Any) -> None:
        """Direct tap entry for scorers the router calls as an OBJECT
        (``score_with_ids`` — serving/history.py SeqScorer): there is no
        score_fn to :meth:`wrap`, so the scorer offers each resolved
        batch itself. Same budget/queue bounds, same no-challenger cost
        (one attribute read)."""
        version = self._armed_version
        if version is not None:
            self._offer(version, x, proba)

    def _offer(self, version: int, x: np.ndarray, proba: Any) -> None:
        with self._mu:
            if self.max_rows_per_s > 0:
                now = time.monotonic()
                self._tokens = min(
                    self.max_rows_per_s,
                    self._tokens
                    + (now - self._t_refill) * self.max_rows_per_s,
                )
                self._t_refill = now
                if self._tokens <= 0:
                    # over the sampling budget: this batch is not shadow-
                    # scored (the verdict window just grows), and the hot
                    # path paid one clock read + one compare for it
                    if self._c_dropped is not None:
                        self._c_dropped.inc(len(x))
                    return
                self._tokens -= len(x)  # may go negative: deficit charge
            if self.max_queued_rows > 0 and len(x) > self.max_queued_rows:
                # an offer that can NEVER fit drops itself — evicting the
                # whole queue of serviceable pairs for it would be the
                # oversize-arrival defect the PR 6 batcher hardening
                # fixed (the verdict window just grows)
                if self._c_dropped is not None:
                    self._c_dropped.inc(len(x))
                return
            while self._queue and (
                    len(self._queue) >= self.max_queued_batches
                    or (self.max_queued_rows > 0
                        and self._queued_rows + len(x)
                        > self.max_queued_rows)):
                _, x_old, _ = self._queue.popleft()
                self._queued_rows -= len(x_old)
                if self._c_dropped is not None:
                    self._c_dropped.inc(len(x_old))
            self._queue.append((version, x, np.asarray(proba)))
            self._queued_rows += len(x)
        if self._c_batches is not None:
            self._c_batches.inc()

    # -- control (the lifecycle controller drives these) -------------------
    def arm(self, version: int) -> None:
        with self._mu:
            self._queue.clear()  # pairs from an older candidate are noise
            self._queued_rows = 0
            self._armed_version = int(version)

    def disarm(self) -> None:
        with self._mu:
            self._armed_version = None
            self._queue.clear()
            self._queued_rows = 0

    @property
    def armed_version(self) -> int | None:
        return self._armed_version

    def qsize(self) -> int:
        with self._mu:
            return len(self._queue)

    # -- worker ------------------------------------------------------------
    def step(self, max_batches: int = 16) -> int:
        """Drain up to ``max_batches`` tapped batches: challenger-score each
        and produce the paired record. Returns rows shadow-scored."""
        rows = 0
        for _ in range(max_batches):
            with self._mu:
                if not self._queue:
                    return rows
                version, x, champ = self._queue.popleft()
                self._queued_rows -= len(x)
            if version != self._armed_version:
                continue  # stale pair from a superseded candidate
            try:
                chall = self.scorer.challenger_score(x)
            except Exception:  # noqa: BLE001 - challenger gone/broken: skip
                if self._c_errors is not None:
                    self._c_errors.inc()
                continue
            self.broker.produce(
                self.topic,
                {
                    "version": int(version),
                    "champion": np.asarray(champ, np.float32).tolist(),
                    "challenger": np.asarray(chall, np.float32).tolist(),
                },
            )
            rows += len(chall)
            if self._c_rows is not None:
                self._c_rows.inc(len(chall))
                self._c_pairs.inc()
        return rows

    # -- supervisor-shaped daemon surface ----------------------------------
    def reset(self) -> None:
        self._stop.clear()

    def run(self, interval_s: float = 0.05) -> None:
        while not self._stop.is_set():
            if self.step() == 0:
                self._stop.wait(interval_s)

    def stop(self) -> None:
        self._stop.set()
