"""ModelVersion lineage + transition audit trail, persisted across restarts.

Every retrain candidate becomes a :class:`ModelVersion`: a monotonically
increasing id, its parent (the champion it was trained from), the label
watermark (how many labels the trainer had consumed when it produced the
candidate — the provenance question "which feedback shaped this model"),
a checkpoint ref (the step the params were saved under via
:class:`ccfd_tpu.parallel.checkpoint.CheckpointManager`), and the eval
metrics recorded at each gate.

The store is the compliance surface the LLMOps-for-fraud/AML line of work
argues for (PAPERS.md): every stage transition appends an audit event
(who/when/why), and the whole lineage persists as one JSON file
(tmp+rename, crash-safe) so a restarted controller resumes with the same
champion, the same next-version counter, and the full history. ``path=None``
keeps everything in memory (tests, ephemeral runs).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Any, Iterable

from ccfd_tpu.runtime import durability

# stage vocabulary — the state machine the controller walks plus the
# terminal stamps the audit trail distinguishes
STAGES = (
    "TRAIN",        # created, not yet scoring anything
    "SHADOW",       # scoring live batches off the critical path
    "CANARY",       # serving a hash-split slice of live traffic
    "CHAMPION",     # the serving model
    "REJECTED",     # failed a SHADOW gate; never served
    "ROLLED_BACK",  # breached a CANARY guardrail; slice withdrawn
    "SUPERSEDED",   # a newer candidate replaced it before a verdict
    "RETIRED",      # a former champion after a promotion
)


@dataclasses.dataclass
class ModelVersion:
    version: int
    parent: int | None
    stage: str = "TRAIN"
    label_watermark: int = 0
    checkpoint_step: int | None = None
    # sha256 over the FULLY-GATHERED checkpoint bytes
    # (parallel/partition.params_fingerprint): device-count-invariant —
    # the same champion audits as the same hash whether its params served
    # sharded over 8 chips or whole on one (ROADMAP item 2's provenance
    # requirement under sharded serving)
    checkpoint_hash: str | None = None
    created_at: float = 0.0
    metrics: dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "ModelVersion":
        return ModelVersion(
            version=int(d["version"]),
            parent=(None if d.get("parent") is None else int(d["parent"])),
            stage=str(d.get("stage", "TRAIN")),
            label_watermark=int(d.get("label_watermark", 0)),
            checkpoint_step=(None if d.get("checkpoint_step") is None
                             else int(d["checkpoint_step"])),
            checkpoint_hash=(None if d.get("checkpoint_hash") is None
                             else str(d["checkpoint_hash"])),
            created_at=float(d.get("created_at", 0.0)),
            metrics=dict(d.get("metrics", {})),
        )


class VersionStore:
    """Thread-safe lineage + audit persistence (one JSON file).

    The audit list is bounded (``max_audit_events``, oldest trimmed with a
    one-time truncation marker) so a long-lived deployment retraining
    every few minutes cannot grow the rewrite-on-every-transition JSON
    without limit; deployments needing the unbounded stream mirror events
    to the bus audit topic instead of this file."""

    def __init__(self, path: str | None = None,
                 max_audit_events: int = 8192,
                 max_versions: int = 512,
                 recover: bool = True):
        self.path = path
        self.max_audit_events = int(max_audit_events)
        # terminal-version bound (same rationale as the audit cap: the
        # whole file rewrites on every transition): oldest REJECTED/
        # SUPERSEDED/ROLLED_BACK/RETIRED versions age out past the cap;
        # the champion and any in-flight candidate are never evicted
        self.max_versions = int(max_versions)
        self._mu = threading.Lock()
        self._versions: dict[int, ModelVersion] = {}
        self._audit: list[dict[str, Any]] = []
        self._next = 1
        if path and recover:
            # recover=False is the read-only inspection surface: it must
            # never mutate the live directory (no sweep, no quarantine) —
            # a live writer's in-flight unique tmp is not debris
            durability.sweep_tmp(os.path.dirname(os.path.abspath(path)))
        if path and (os.path.exists(path) or durability.has_generations(path)):
            try:
                self._load(recover=recover)
            except (OSError, ValueError, KeyError, TypeError,
                    durability.CorruptArtifactError) as e:
                if not recover:
                    # read-only consumers (the inspection CLI) must
                    # REPORT corruption, never quarantine the live file
                    raise
                # NOTHING verifies — not the live file (quarantined to
                # *.corrupt by the durability layer) nor any retained
                # generation: the last resort is a fresh lineage rather
                # than a bricked bring-up (the loss is logged; the
                # champion re-bootstraps from the scorer's live params)
                import logging

                logging.getLogger(__name__).error(
                    "lifecycle lineage %s unreadable (%r) with no "
                    "verifiable generation; starting a FRESH lineage",
                    path, e)
                self._versions, self._audit, self._next = {}, [], 1

    # -- persistence -------------------------------------------------------
    def _load(self, recover: bool = True) -> None:
        # verified read: a torn/bit-flipped lineage quarantines and falls
        # back to the last-good retained generation (runtime/durability.py).
        # A LEGACY (unframed) file carries no checksum, so its corruption
        # only surfaces at the JSON parse — quarantine it then and retry,
        # which reads straight from the generations.
        import json

        data = None
        for attempt in (0, 1):
            payload = durability.read_artifact(
                self.path, artifact="lineage", fallback=True,
                quarantine=recover)
            try:
                data = json.loads(payload)
                break
            except ValueError:
                if not recover or attempt:
                    raise
                durability.note("corrupt", artifact="lineage")
                # ccfd-lint: disable=durability-seam -- quarantine rename (the sanctioned exception): counted via note() the line above
                os.replace(self.path, f"{self.path}.corrupt")
        self._versions = {
            int(v["version"]): ModelVersion.from_dict(v)
            for v in data.get("versions", [])
        }
        self._audit = list(data.get("audit", []))
        # the counter must survive restarts even past deleted checkpoints:
        # persisted explicitly AND floored by the observed ids
        self._next = max(
            int(data.get("next_version", 1)),
            max(self._versions, default=0) + 1,
        )

    def _save_locked(self) -> None:
        if not self.path:
            return
        # checksummed + fsynced + atomic, with generation retention: the
        # constructor's verified read falls back to the newest retained
        # generation when the live file is torn or bit-flipped. A failed
        # write (full disk, injected fault) keeps the last-good state —
        # lineage lives in memory and lands on the next transition.
        durability.write_json_artifact(
            self.path,
            {
                "next_version": self._next,
                "versions": [
                    v.to_dict() for _, v in sorted(self._versions.items())
                ],
                "audit": self._audit,
            },
            artifact="lineage",
            indent=1,
        )

    # -- lineage -----------------------------------------------------------
    def create(
        self,
        parent: int | None,
        label_watermark: int = 0,
        checkpoint_step: int | None = None,
        stage: str = "TRAIN",
    ) -> ModelVersion:
        with self._mu:
            v = ModelVersion(
                version=self._next,
                parent=parent,
                stage=stage,
                label_watermark=int(label_watermark),
                checkpoint_step=checkpoint_step,
                created_at=time.time(),
            )
            self._next += 1
            self._versions[v.version] = v
            self._append_event_locked(
                v.version, "created",
                {"parent": parent, "label_watermark": v.label_watermark},
            )
            self._trim_versions_locked()
            self._save_locked()
            return v

    _TERMINAL = ("REJECTED", "SUPERSEDED", "ROLLED_BACK", "RETIRED")

    def _trim_versions_locked(self) -> None:
        excess = len(self._versions) - self.max_versions
        if excess <= 0:
            return
        terminal = sorted(
            (v for v in self._versions.values() if v.stage in self._TERMINAL),
            key=lambda v: v.version,
        )[:excess]
        if not terminal:
            return  # only live versions: never evict those
        for v in terminal:
            del self._versions[v.version]
        self._append_event_locked(
            None, "versions_trimmed",
            {"evicted": [v.version for v in terminal],
             "note": "oldest terminal versions aged out by the "
                     "max_versions bound"},
        )

    def set_stage(
        self,
        version: int,
        stage: str,
        reason: str = "",
        metrics: dict[str, Any] | None = None,
    ) -> ModelVersion:
        if stage not in STAGES:
            raise ValueError(f"unknown stage {stage!r}; known: {STAGES}")
        with self._mu:
            v = self._versions[int(version)]
            prev = v.stage
            v.stage = stage
            if metrics:
                v.metrics.update(metrics)
            self._append_event_locked(
                v.version, "stage",
                {"from": prev, "to": stage, "reason": reason,
                 **({"metrics": metrics} if metrics else {})},
            )
            self._save_locked()
            return v

    def record_event(self, version: int | None, event: str,
                     detail: dict[str, Any] | None = None) -> None:
        with self._mu:
            self._append_event_locked(version, event, detail or {})
            self._save_locked()

    def set_checkpoint(self, version: int, checkpoint_step: int,
                       checkpoint_hash: str | None = None) -> None:
        with self._mu:
            v = self._versions[int(version)]
            v.checkpoint_step = int(checkpoint_step)
            if checkpoint_hash is not None:
                v.checkpoint_hash = str(checkpoint_hash)
            self._save_locked()

    def _append_event_locked(self, version: int | None, event: str,
                             detail: dict[str, Any]) -> None:
        self._audit.append(
            {"ts": time.time(), "version": version, "event": event,
             "detail": detail}
        )
        if len(self._audit) > self.max_audit_events:
            trimmed = len(self._audit) - self.max_audit_events
            self._audit = self._audit[trimmed:]
            if self._audit[0].get("event") != "audit_trimmed":
                self._audit.insert(0, {
                    "ts": time.time(), "version": None,
                    "event": "audit_trimmed",
                    "detail": {"note": "older events dropped by the "
                                       "max_audit_events bound"},
                })

    # -- queries -----------------------------------------------------------
    def get(self, version: int) -> ModelVersion:
        with self._mu:
            return self._versions[int(version)]

    def versions(self) -> list[ModelVersion]:
        with self._mu:
            return [v for _, v in sorted(self._versions.items())]

    def champion(self) -> ModelVersion | None:
        with self._mu:
            champs = [v for v in self._versions.values()
                      if v.stage == "CHAMPION"]
            # at most one champion by construction; latest wins defensively
            return max(champs, key=lambda v: v.version, default=None)

    def in_stage(self, *stages: str) -> list[ModelVersion]:
        with self._mu:
            return sorted(
                (v for v in self._versions.values() if v.stage in stages),
                key=lambda v: v.version,
            )

    def audit_trail(self, version: int | None = None) -> list[dict[str, Any]]:
        with self._mu:
            if version is None:
                return list(self._audit)
            return [e for e in self._audit if e["version"] == version]

    def lineage(self, version: int) -> Iterable[ModelVersion]:
        """The version and its ancestors, newest first."""
        cur: int | None = int(version)
        while cur is not None:
            with self._mu:
                v = self._versions.get(cur)
            if v is None:
                return
            yield v
            cur = v.parent
