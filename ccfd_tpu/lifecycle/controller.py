"""Lifecycle controller: guardrailed SHADOW -> CANARY -> PROMOTE | ROLLBACK.

The governed replacement for the trainer's blind ``swap_params``:

- ``submit_candidate(params, label_watermark)`` (called by
  :class:`~ccfd_tpu.parallel.online.OnlineTrainer`) checkpoints the
  candidate (:class:`~ccfd_tpu.parallel.checkpoint.CheckpointManager`),
  records its lineage, installs it in the scorer's double-buffered
  challenger slot and arms the shadow tap. A candidate submitted while one
  is already in flight supersedes it (newest feedback wins; the audit trail
  records the supersession).
- **SHADOW gate**: once ``min_labels`` labels and ``min_shadow_rows``
  shadow pairs accumulate, the candidate is judged — challenger AUC within
  ``auc_margin`` of the champion's, alert-rate delta under
  ``max_alert_rate_delta``, score-distribution PSI under ``max_score_psi``.
  Any breach REJECTS the candidate (champion untouched).
- **CANARY**: the survivor serves a deterministic ``canary_weight`` slice
  of live traffic through the :class:`CanaryGate`, which drives the
  :mod:`ccfd_tpu.serving.graph` ``hash_split`` ROUTER's per-row
  traffic-split (the same hash, the same weights semantics — stable across
  processes and jit re-traces, test-asserted). Guardrails stay armed the
  whole phase, and a scorer-edge circuit breaker leaving CLOSED is itself
  a breach: any of them auto-rolls back to the champion checkpoint and
  records the audit event.
- **PROMOTE**: after ``canary_min_labels`` further labels with guardrails
  green, the challenger's params swap into the serving scorer, the old
  champion retires, and the lineage/audit trail records the promotion.

Everything is observable: ``ccfd_lifecycle_stage`` (0 idle / 1 shadow /
2 canary), ``ccfd_lifecycle_promotions_total`` /
``ccfd_lifecycle_rollbacks_total`` / ``ccfd_lifecycle_rejections_total``,
champion/candidate version gauges, and per-arm canary row counters.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Any, Callable

import jax
import numpy as np

from ccfd_tpu.config import Config
from ccfd_tpu.lifecycle.evaluator import EvalSnapshot, ShadowEvaluator
from ccfd_tpu.lifecycle.shadow import ShadowTap
from ccfd_tpu.lifecycle.versions import VersionStore

log = logging.getLogger(__name__)

# ccfd_lifecycle_stage gauge values
STAGE_IDLE, STAGE_SHADOW, STAGE_CANARY = 0, 1, 2


@dataclasses.dataclass(frozen=True)
class Guardrails:
    """The gates a candidate must clear; every ceiling also stays armed
    through CANARY (a breach there triggers auto-rollback)."""

    min_labels: int = 128          # labels joined before a SHADOW verdict
    min_shadow_rows: int = 1024    # shadow pairs before PSI/alert gates bind
    auc_margin: float = 0.01       # challenger AUC >= champion AUC - margin
    max_alert_rate_delta: float = 0.10  # extra alert fraction allowed
    max_score_psi: float = 0.25    # drift ceiling (PSI > 0.25 = action)
    canary_weight: float = 0.10    # traffic fraction served by the canary
    canary_min_labels: int = 64    # labels DURING canary before promotion
    # submission coalescing: a trainer that retrains on every label batch
    # can submit faster than a verdict window fills, superseding every
    # candidate before judgment — a livelock where nothing ever promotes.
    # Submissions inside this interval of the last ACCEPTED one are
    # coalesced (counted, no version created); the in-flight candidate
    # keeps its evidence and the trainer's next submission carries the
    # newer labels anyway. 0 = accept every submission (tests/drills).
    min_submit_interval_s: float = 30.0


class CanaryGate:
    """Per-row deterministic traffic split between champion and challenger.

    Drives the serving-graph ``hash_split`` ROUTER's weights: arm
    assignment uses :func:`ccfd_tpu.serving.graph.hash_split_arms_numpy`,
    the host mirror of the compiled router component, so a row lands on
    the same arm here, in a compiled canary graph, in another process, and
    across jit re-traces. Champion rows keep the device-scored result;
    challenger rows re-score on the challenger slot's host forward (the
    canary slice is small by construction, so the extra host work is
    bounded by ``canary_weight``)."""

    def __init__(self, scorer: Any, registry: Any = None):
        self.scorer = scorer
        self._active = False  # hot-path gate: plain attr read
        self._weights: tuple[float, float] = (1.0, 0.0)
        self._c_rows = self._c_errors = None
        if registry is not None:
            self._c_rows = registry.counter(
                "ccfd_lifecycle_canary_rows_total",
                "rows served during canary, by arm",
            )
            self._c_errors = registry.counter(
                "ccfd_lifecycle_canary_errors_total",
                "challenger canary-score failures (rows fell back to the "
                "champion score)",
            )

    def activate(self, weight: float) -> None:
        w = min(max(float(weight), 0.0), 1.0)
        self._weights = (1.0 - w, w)
        self._active = True

    def deactivate(self) -> None:
        self._active = False
        self._weights = (1.0, 0.0)

    @property
    def active(self) -> bool:
        return self._active

    @property
    def weights(self) -> tuple[float, float]:
        return self._weights

    def apply(self, x: np.ndarray, proba: np.ndarray,
              rescore: Callable[[np.ndarray], np.ndarray] | None = None,
              ) -> np.ndarray:
        """Override one batch's challenger arm. ``x`` (B, F) drives the
        deterministic hash split; ``rescore(mask) -> (n_chall,) scores``
        lets context-aware scorers (the SeqScorer's history-conditioned
        lane) re-score the challenger arm against the SAME assembled
        contexts — default is the challenger slot's cold forward on the
        masked feature rows (the row lane)."""
        if not self._active:
            return proba
        from ccfd_tpu.serving.graph import hash_split_arms_numpy

        arms = hash_split_arms_numpy(x, self._weights)
        mask = arms == 1
        n_chall = int(mask.sum())
        if n_chall:
            try:
                if rescore is not None:
                    chall = rescore(mask)
                else:
                    chall = self.scorer.challenger_score(
                        np.asarray(x, np.float32)[mask])
            except Exception:  # noqa: BLE001 - challenger gone mid-swap:
                # champion scores stand; the controller sees the error
                # counter and the breaker sees nothing (host-side only)
                if self._c_errors is not None:
                    self._c_errors.inc(n_chall)
                return proba
            proba = np.array(proba, np.float32, copy=True)
            proba[mask] = chall
        if self._c_rows is not None:
            self._c_rows.inc(len(x) - n_chall,
                             labels={"arm": "champion"})
            if n_chall:
                self._c_rows.inc(n_chall, labels={"arm": "challenger"})
        return proba

    def wrap(self, score_fn: Callable[[np.ndarray], np.ndarray]) -> Callable:
        def gated(x: np.ndarray) -> np.ndarray:
            proba = score_fn(x)
            if not self._active:
                return proba
            return self.apply(x, proba)

        gated.__wrapped__ = score_fn
        return gated


class LifecycleController:
    """Owns the candidate state machine; supervisor-shaped daemon."""

    def __init__(
        self,
        cfg: Config,
        scorer: Any,
        store: VersionStore,
        checkpoints: Any,
        shadow: ShadowTap,
        evaluator: ShadowEvaluator,
        gate: CanaryGate | None = None,
        guardrails: Guardrails | None = None,
        registry: Any = None,
        breaker: Any = None,
        storage_pin: Callable[[str], None] | None = None,
        storage_unpin: Callable[[], None] | None = None,
    ):
        self.cfg = cfg
        self.scorer = scorer
        self.store = store
        self.checkpoints = checkpoints
        self.shadow = shadow
        self.evaluator = evaluator
        self.gate = gate if gate is not None else CanaryGate(scorer, registry)
        self.guardrails = guardrails or Guardrails()
        self.breaker = breaker  # scorer-edge CircuitBreaker (may be None)
        # storage-integrity pin (runtime/durability.StoragePinGate): when
        # the champion's checkpoint — and every verifiable fallback step —
        # is corrupt, serving must pin to the RULES tier through the PR 11
        # heal-gate seam rather than publish an unverified tree; cleared
        # the moment a verified tree is published again
        self._storage_pin = storage_pin
        self._storage_unpin = storage_unpin
        self.storage_pinned = False
        # rebase hook (wired by the operator to OnlineTrainer.rebase): on
        # REJECT/ROLLBACK the trainer's training state re-bases onto the
        # champion, so later candidates genuinely DESCEND from the
        # champion the lineage records as their parent — without it the
        # trainer keeps training on rejected weights and the audit
        # trail's provenance claim is false
        self.trainer_rebase: Callable[[Any], None] | None = None
        self._mu = threading.RLock()
        self._stop = threading.Event()

        self._candidate: int | None = None
        self._candidate_params: Any = None
        self._stage = STAGE_IDLE

        r = registry
        self._g_stage = self._g_champion = self._g_candidate = None
        self._c_promoted = self._c_rolled_back = None
        self._c_rejected = self._c_candidates = None
        self._c_coalesced = None
        self._last_accept_mono: float | None = None
        if r is not None:
            self._g_stage = r.gauge(
                "ccfd_lifecycle_stage",
                "candidate stage: 0 idle, 1 shadow, 2 canary",
            )
            self._g_stage.set(STAGE_IDLE)
            self._g_champion = r.gauge(
                "ccfd_lifecycle_champion_version", "serving model version"
            )
            self._g_candidate = r.gauge(
                "ccfd_lifecycle_candidate_version",
                "candidate version in flight (-1 = none)",
            )
            self._g_candidate.set(-1)
            self._c_candidates = r.counter(
                "ccfd_lifecycle_candidates_total",
                "retrain candidates submitted to the lifecycle",
            )
            self._c_promoted = r.counter(
                "ccfd_lifecycle_promotions_total",
                "candidates promoted to champion through the full gate",
            )
            self._c_rolled_back = r.counter(
                "ccfd_lifecycle_rollbacks_total",
                "canary auto-rollbacks to the champion checkpoint",
            )
            self._c_rejected = r.counter(
                "ccfd_lifecycle_rejections_total",
                "candidates rejected at the SHADOW gate",
            )
            self._c_coalesced = r.counter(
                "ccfd_lifecycle_submissions_coalesced_total",
                "trainer submissions coalesced into the in-flight "
                "candidate (min_submit_interval_s pacing)",
            )

        # champion bootstrap: resume the persisted lineage, or version the
        # scorer's current params as the genesis champion
        champ = store.champion()
        if champ is None:
            v = store.create(parent=None, stage="TRAIN")
            self._champion_params = self._host_copy(scorer.params)
            # pin BEFORE save: save() runs GC, and the champion's
            # checkpoint must survive any number of later candidates
            checkpoints.pinned = {v.version}
            checkpoints.save(v.version, self._champion_params)
            store.set_checkpoint(
                v.version, v.version,
                checkpoint_hash=self._fingerprint(self._champion_params))
            store.set_stage(v.version, "CHAMPION", reason="bootstrap")
            self.champion = v.version
        else:
            self.champion = champ.version
            if champ.checkpoint_step is not None:
                checkpoints.pinned = {champ.checkpoint_step}
            self._champion_params = self._restore_params(champ)
            # re-assert the persisted champion INTO SERVING: the scorer
            # was just built from its boot params, and the lineage says
            # champ.version serves — without this swap the audit trail
            # and the live model disagree after every restart
            self.scorer.swap_params(self._champion_params)
            restored_hash = self._fingerprint(self._champion_params)
            if (champ.checkpoint_hash is not None and restored_hash
                    and restored_hash != champ.checkpoint_hash):
                # the restored bytes are not the recorded champion: the
                # checkpoint was GC'd/corrupted and the fallback (live
                # scorer params) took over — serve, but say so loudly,
                # and RE-STAMP the lineage record so the next restart of
                # the now-stable tree doesn't re-raise the same alarm
                # (the audit event below preserves the divergence)
                log.error(
                    "lifecycle restart: champion v%d checkpoint hash "
                    "mismatch (recorded %s, restored %s) — serving the "
                    "restored tree, lineage re-stamped",
                    champ.version, champ.checkpoint_hash[:12],
                    restored_hash[:12])
                if champ.checkpoint_step is not None:
                    store.set_checkpoint(champ.version,
                                         champ.checkpoint_step,
                                         checkpoint_hash=restored_hash)
            store.record_event(self.champion, "restart_restore",
                               {"checkpoint": champ.checkpoint_step,
                                "checkpoint_hash": restored_hash})
            # interrupted candidates did not survive the restart
            # (challenger slot and gate state are process-local). Stage
            # vocabulary stays truthful: only a candidate that actually
            # SERVED a canary slice is stamped ROLLED_BACK; shadow-only
            # ones were simply displaced (no serving ever changed)
            for v in store.in_stage("CANARY"):
                store.set_stage(v.version, "ROLLED_BACK",
                                reason="controller restart mid-canary")
            for v in store.in_stage("SHADOW", "TRAIN"):
                store.set_stage(v.version, "SUPERSEDED",
                                reason="controller restart")
        if self._g_champion is not None:
            self._g_champion.set(self.champion)

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _host_copy(params: Any) -> Any:
        """Fully-gathered host copy — on a mesh, ``np.array`` of a sharded
        ``jax.Array`` materializes the GLOBAL array (every serving mesh
        here is single-process/fully-addressable), so checkpoints, hashes
        and the challenger slot always see whole trees."""
        return jax.tree.map(lambda a: np.array(a), params)

    @staticmethod
    def _fingerprint(params: Any) -> str | None:
        """Device-count-invariant checkpoint hash (sha256 over the
        fully-gathered bytes, parallel/partition.params_fingerprint)."""
        from ccfd_tpu.parallel.partition import params_fingerprint

        try:
            return params_fingerprint(params)
        except Exception:  # noqa: BLE001 - provenance, not control flow
            log.exception("lifecycle: params fingerprint failed")
            return None

    def _restore_params(self, version) -> Any:
        """Champion params from its checkpoint, with integrity fallback
        (ISSUE 13): a corrupt recorded checkpoint is quarantined by the
        CheckpointManager and the restore walks to the NEWEST VERIFIABLE
        step — the pinned set (the champion's own) first, then the
        remaining steps newest-first, which reaches the parent champion's
        retained checkpoint. When a checkpoint was recorded but NOTHING
        verifies, serving pins to the rules tier (storage_pin) instead of
        publishing an unverified tree; the caller's existing hash-mismatch
        alarm fires and re-stamps the lineage on any fallback serve."""
        from ccfd_tpu.runtime.durability import CorruptArtifactError

        like = self._host_copy(self.scorer.params)
        step = version.checkpoint_step
        if step is None:
            return like  # genesis bootstrap: nothing recorded yet
        order: list[int] = [step]
        order += sorted(self.checkpoints.pinned, reverse=True)
        seen: set[int] = set()
        saw_corrupt = False
        for s in order:
            if s in seen:
                continue
            seen.add(s)
            try:
                restored = self.checkpoints.restore(like, step=s)
            except CorruptArtifactError:
                saw_corrupt = True
                log.error("champion v%d checkpoint step %d corrupt "
                          "(quarantined); trying the next verifiable step",
                          version.version, s)
                continue
            except (FileNotFoundError, OSError, ValueError):
                continue
            if restored is not None:
                self._note_storage_restore(version, s, step)
                return restored[0]
        # the recorded step (and every pin) failed: newest verifiable step
        # of the whole retained history, the parent champion included
        s = self.checkpoints.newest_verified_step()
        if s is not None and s not in seen:
            try:
                restored = self.checkpoints.restore(like, step=s)
                if restored is not None:
                    self._note_storage_restore(version, s, step)
                    return restored[0]
            except (CorruptArtifactError, FileNotFoundError, OSError,
                    ValueError):
                pass
        if not saw_corrupt and self.checkpoints.latest_step() is None:
            # nothing on disk at all — every step MISSING (GC'd root,
            # wiped volume), none corrupt: the scorer's live tree is a
            # healthy verified init, not quarantined evidence. Serve it
            # with the historical warning; the pin is for the
            # corruption-detected case only (saw_corrupt also covers a
            # lone corrupt genesis step the walk just quarantined out of
            # the listing).
            log.warning("champion v%d checkpoint %s missing (no steps on "
                        "disk); using the scorer's live params",
                        version.version, step)
            self._clear_storage_pin()
            return like
        log.error(
            "champion v%d: no checkpoint generation verifies (recorded "
            "step %s); pinning serving to the RULES tier rather than "
            "publishing an unverified tree", version.version, step)
        self._pin_storage(
            f"no verifiable checkpoint for champion v{version.version}")
        return like

    def _note_storage_restore(self, version, served_step: int,
                              recorded_step: int) -> None:
        """A verified tree is about to serve: clear any storage pin, and
        audit a fallback serve (the hash re-stamp alarm in the restart
        path fires on top of this when the bytes differ)."""
        self._clear_storage_pin()
        if served_step != recorded_step:
            self.store.record_event(
                version.version, "storage_fallback_restore",
                {"recorded_step": recorded_step, "served_step": served_step,
                 "note": "recorded checkpoint unverifiable; newest "
                         "verifiable generation served"})

    def _pin_storage(self, reason: str) -> None:
        self.storage_pinned = True
        if self._storage_pin is not None:
            try:
                self._storage_pin(reason)
            except Exception:  # noqa: BLE001 - the pin is protective
                log.exception("storage pin hook failed")
        self.store.record_event(None, "storage_pin", {"reason": reason})

    def _clear_storage_pin(self) -> None:
        if not self.storage_pinned:
            return
        self.storage_pinned = False
        if self._storage_unpin is not None:
            try:
                self._storage_unpin()
            except Exception:  # noqa: BLE001
                log.exception("storage unpin hook failed")
        self.store.record_event(None, "storage_unpin",
                                {"reason": "verified params published"})

    def wrap_score(self, score_fn: Callable) -> Callable:
        """Compose the serving lane: shadow tap inside (sees pure champion
        scores), canary gate outside (overrides the challenger arm). This
        is what the operator hands the router / coalescing batcher."""
        return self.gate.wrap(self.shadow.wrap(score_fn))

    # -- trainer entry point ----------------------------------------------
    def submit_candidate(self, params: Any, label_watermark: int = 0) -> int:
        """Register a retrain candidate and start its SHADOW phase.
        Thread-safe: called from the trainer thread while step() runs on
        the controller's. Returns the new version id."""
        import time as _time

        with self._mu:
            # pacing FIRST (before any param copy — the coalesce branch
            # must cost nothing): a trainer retraining on every label
            # batch must not supersede the in-flight candidate before its
            # verdict window can fill (livelock: nothing would ever
            # promote). Coalesced submissions keep the in-flight
            # candidate and its evidence.
            now = _time.monotonic()
            if (self._candidate is not None
                    and self._last_accept_mono is not None
                    and (now - self._last_accept_mono)
                    < self.guardrails.min_submit_interval_s):
                if self._c_coalesced is not None:
                    self._c_coalesced.inc()
                return self._candidate
            self._last_accept_mono = now
            staged = self._host_copy(params)  # trainer donates its state
            if self._candidate is not None:
                old = self._candidate
                self._clear_candidate_serving()
                self.store.set_stage(
                    old, "SUPERSEDED",
                    reason="newer candidate submitted before a verdict")
            v = self.store.create(
                parent=self.champion, label_watermark=label_watermark)
            self.checkpoints.save(v.version, staged)
            self.store.set_checkpoint(
                v.version, v.version,
                checkpoint_hash=self._fingerprint(staged))
            self._candidate = v.version
            self._candidate_params = staged
            self.scorer.install_challenger(v.version, staged)
            self.evaluator.begin(v.version)
            self.shadow.arm(v.version)
            self._set_stage(STAGE_SHADOW)
            self.store.set_stage(v.version, "SHADOW")
            if self._c_candidates is not None:
                self._c_candidates.inc()
            if self._g_candidate is not None:
                self._g_candidate.set(v.version)
            return v.version

    # -- state machine -----------------------------------------------------
    def _set_stage(self, stage: int) -> None:
        self._stage = stage
        if self._g_stage is not None:
            self._g_stage.set(stage)

    @property
    def stage(self) -> int:
        return self._stage

    @property
    def candidate(self) -> int | None:
        return self._candidate

    def _clear_candidate_serving(self) -> None:
        """Withdraw the candidate from every serving surface (under _mu)."""
        self.gate.deactivate()
        self.shadow.disarm()
        self.scorer.clear_challenger()
        self.evaluator.end()
        self._candidate = None
        self._candidate_params = None
        self._set_stage(STAGE_IDLE)
        if self._g_candidate is not None:
            self._g_candidate.set(-1)

    def _shadow_breaches(self, s: EvalSnapshot) -> list[str]:
        g = self.guardrails
        breaches = []
        if (np.isfinite(s.auc_champion) and np.isfinite(s.auc_challenger)
                and s.auc_challenger < s.auc_champion - g.auc_margin):
            breaches.append(
                f"auc {s.auc_challenger:.4f} < champion "
                f"{s.auc_champion:.4f} - margin {g.auc_margin}")
        if (np.isfinite(s.alert_rate_delta)
                and abs(s.alert_rate_delta) > g.max_alert_rate_delta):
            breaches.append(
                f"alert_rate_delta {s.alert_rate_delta:+.4f} exceeds "
                f"{g.max_alert_rate_delta}")
        if np.isfinite(s.score_psi) and s.score_psi > g.max_score_psi:
            breaches.append(
                f"score_psi {s.score_psi:.4f} exceeds {g.max_score_psi}")
        return breaches

    def step(self) -> bool:
        """One control cycle: fold new evidence, judge the gates. Returns
        whether a transition happened (so the run loop can idle). The poll
        runs under _mu too: the trainer thread's submit_candidate resets
        the same evaluator accumulators (begin/end), and an unserialized
        poll could split its paired extends across the reset."""
        with self._mu:
            self.evaluator.poll()
            if self._candidate is None:
                return False
            if self._stage == STAGE_SHADOW:
                return self._step_shadow()
            if self._stage == STAGE_CANARY:
                return self._step_canary()
            return False

    def _step_shadow(self) -> bool:
        g = self.guardrails
        # cheap counters gate the expensive snapshot: a candidate parked
        # below its thresholds must not pay full-history rank sorts (under
        # _mu, blocking the trainer's submits) every 250 ms tick
        if (self.evaluator.n_labels < g.min_labels
                or self.evaluator.n_shadow_rows < g.min_shadow_rows):
            return False
        snap = self.evaluator.snapshot()
        breaches = self._shadow_breaches(snap)
        if breaches:
            self._reject(snap, breaches)
            return True
        self._enter_canary(snap)
        return True

    def _step_canary(self) -> bool:
        g = self.guardrails
        if self.breaker is not None and self.breaker.state != "closed":
            self._rollback(
                self.evaluator.snapshot(),
                [f"scorer-edge breaker {self.breaker.state}"])
            return True
        # judge the CANARY WINDOW (evidence since _enter_canary's mark),
        # not the running total: a regression that only shows up under
        # canary serving must not be diluted by the green shadow history.
        # Distribution gates bind once the window has a meaningful sample;
        # the AUC gate binds at the promotion decision's label count (a
        # handful of window labels would be noise, not evidence).
        w = self.evaluator.snapshot_window()
        breaches: list[str] = []
        if w.n_shadow_rows >= max(1, self.guardrails.min_shadow_rows // 4):
            if (np.isfinite(w.alert_rate_delta)
                    and abs(w.alert_rate_delta) > g.max_alert_rate_delta):
                breaches.append(
                    f"canary alert_rate_delta {w.alert_rate_delta:+.4f} "
                    f"exceeds {g.max_alert_rate_delta}")
            if np.isfinite(w.score_psi) and w.score_psi > g.max_score_psi:
                breaches.append(
                    f"canary score_psi {w.score_psi:.4f} exceeds "
                    f"{g.max_score_psi}")
        ready = w.n_labels >= g.canary_min_labels
        if ready and (np.isfinite(w.auc_champion)
                      and np.isfinite(w.auc_challenger)
                      and w.auc_challenger < w.auc_champion - g.auc_margin):
            breaches.append(
                f"canary auc {w.auc_challenger:.4f} < champion "
                f"{w.auc_champion:.4f} - margin {g.auc_margin}")
        if breaches:
            self._rollback(w, breaches)
            return True
        if ready:
            # the full-history snapshot is the promote record's metrics;
            # computed only here, at the decision, not per tick
            self._promote(self.evaluator.snapshot())
            return True
        return False

    def _rebase_trainer(self) -> None:
        """Point the trainer back at the champion's weights so the next
        candidate descends from the lineage's recorded parent, not from
        the just-discarded candidate."""
        if self.trainer_rebase is None:
            return
        try:
            self.trainer_rebase(self._champion_params)
        except Exception:  # noqa: BLE001 - a dead trainer must not block
            log.exception("lifecycle: trainer rebase after discard failed")

    def _reject(self, snap: EvalSnapshot, breaches: list[str]) -> None:
        v = self._candidate
        log.warning("lifecycle: candidate v%d REJECTED in shadow: %s",
                    v, "; ".join(breaches))
        self._clear_candidate_serving()
        self.store.set_stage(v, "REJECTED", reason="; ".join(breaches),
                             metrics=snap.to_dict())
        if self._c_rejected is not None:
            self._c_rejected.inc()
        self._rebase_trainer()

    def _enter_canary(self, snap: EvalSnapshot) -> None:
        g = self.guardrails
        v = self._candidate
        # canary guardrails judge the evidence window that starts HERE
        self.evaluator.mark()
        self.gate.activate(g.canary_weight)
        self._set_stage(STAGE_CANARY)
        self.store.set_stage(
            v, "CANARY",
            reason=f"shadow gates passed; weight={g.canary_weight}",
            metrics=snap.to_dict())
        log.info("lifecycle: candidate v%d entered canary at weight %.2f",
                 v, g.canary_weight)

    def _promote(self, snap: EvalSnapshot) -> None:
        v = self._candidate
        params = self._candidate_params
        old_champion = self.champion
        self.gate.deactivate()
        self.scorer.swap_params(params)
        # the promoted tree was checkpointed (verified) at submit: a
        # storage pin from an earlier unverifiable restart clears here
        self._clear_storage_pin()
        self.shadow.disarm()
        self.scorer.clear_challenger()
        self.evaluator.end()
        self.champion = v
        self._champion_params = params
        # the new champion's checkpoint is now the rollback/restart
        # anchor: re-point the GC pin at it (the retired one may age out)
        self.checkpoints.pinned = {v}
        self._candidate = None
        self._candidate_params = None
        self._set_stage(STAGE_IDLE)
        self.store.set_stage(old_champion, "RETIRED",
                             reason=f"superseded by v{v}")
        self.store.set_stage(v, "CHAMPION",
                             reason=f"canary gates passed over "
                                    f"{snap.n_labels} labels",
                             metrics=snap.to_dict())
        if self._c_promoted is not None:
            self._c_promoted.inc()
        if self._g_champion is not None:
            self._g_champion.set(v)
        if self._g_candidate is not None:
            self._g_candidate.set(-1)
        log.info("lifecycle: candidate v%d PROMOTED (champion was v%d)",
                 v, old_champion)

    def _rollback(self, snap: EvalSnapshot, breaches: list[str]) -> None:
        v = self._candidate
        log.warning("lifecycle: candidate v%d ROLLED BACK from canary: %s",
                    v, "; ".join(breaches))
        self._clear_candidate_serving()
        # restore the champion checkpoint into serving: the canary slice
        # disappears with the gate, and the champion params re-assert so a
        # raced promote/partial swap can never leave mixed weights live
        champion = self.store.get(self.champion)
        params = self._restore_params(champion)
        self.scorer.swap_params(params)
        self._champion_params = params
        self.store.set_stage(v, "ROLLED_BACK", reason="; ".join(breaches),
                             metrics=snap.to_dict())
        self.store.record_event(
            self.champion, "rollback_restore",
            {"from_candidate": v, "checkpoint": champion.checkpoint_step,
             "checkpoint_hash": self._fingerprint(params)})
        if self._c_rolled_back is not None:
            self._c_rolled_back.inc()
        self._rebase_trainer()

    def restore_champion(self) -> None:
        """Re-assert the champion's CHECKPOINT as the serving params —
        the device heal ladder's respawn rung (runtime/heal.py): a
        quarantined scorer respawns from the durable champion checkpoint,
        not from whatever tree the wedge left on device. Serialized under
        the controller lock so a respawn racing a concurrent
        rollback/promotion cannot interleave half of each swap: whichever
        runs second re-asserts a complete, consistent champion tree (the
        heal-vs-recovery invariant the PR 4 end-state assertion extends
        to: serving params == champion checkpoint)."""
        with self._mu:
            champion = self.store.get(self.champion)
            params = self._restore_params(champion)
            self.scorer.swap_params(params)
            self._champion_params = params
            self.store.record_event(
                self.champion, "heal_respawn_restore",
                {"checkpoint": champion.checkpoint_step,
                 "checkpoint_hash": self._fingerprint(params)})

    def resolve_for_shutdown(self) -> None:
        """Deterministic quiesce: an in-flight candidate is withdrawn so
        the pool is left serving exactly one version (soak/drill
        teardown). Only a candidate actually SERVING a canary slice takes
        the rollback path (champion checkpoint re-asserted, rollback
        counter) — a shadow-only candidate never changed serving, so it
        is stamped SUPERSEDED without touching the champion or the
        canary-rollback alerting metric."""
        with self._mu:
            if self._candidate is None:
                return
            snap = self.evaluator.snapshot()
            if self._stage == STAGE_CANARY:
                self._rollback(snap, ["shutdown with candidate mid-canary"])
                return
            v = self._candidate
            self._clear_candidate_serving()
            self.store.set_stage(
                v, "SUPERSEDED",
                reason="shutdown with candidate in shadow",
                metrics=snap.to_dict())

    def serving_consistent(self) -> bool:
        """True when serving state matches the state machine: challenger
        slot and canary gate exist exactly when a candidate is in flight,
        and the lineage has exactly one champion."""
        with self._mu:
            champ = self.store.champion()
            if champ is None or champ.version != self.champion:
                return False
            has_challenger = self.scorer.challenger_version is not None
            if self._candidate is None:
                return not has_challenger and not self.gate.active
            if self._stage == STAGE_SHADOW:
                return has_challenger and not self.gate.active
            return has_challenger and self.gate.active

    # -- supervisor-shaped daemon surface ----------------------------------
    def reset(self) -> None:
        self._stop.clear()

    def run(self, interval_s: float = 0.25) -> None:
        while not self._stop.is_set():
            if not self.step():
                self._stop.wait(interval_s)

    def stop(self) -> None:
        self._stop.set()

    def close(self) -> None:
        self.stop()
        self.evaluator.close()
