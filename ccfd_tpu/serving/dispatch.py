"""Deadline-bounded device dispatch for the serving path.

Reference parity: the reference's only failure knob on the scoring hop is the
*client-side* HTTP timeout ``SELDON_TIMEOUT`` (`/root/reference/README.md:386-393`).
On a TPU attachment that can wedge mid-dispatch (the tunnel hangs inside a
device sync, so the blocked thread never returns), a client-side timeout alone
leaves the *server* accumulating stuck taker threads and an unbounded p99.
This module is the server-side half: device work runs on a small pool of
sacrificial threads; the caller waits at most a deadline, and on expiry the
scorer falls back to its host tier (or raises :class:`ScorerTimeout`, which
the REST fronts map to 503) while a background probe watches for the
attachment to heal.

A truly wedged dispatch thread cannot be cancelled (the hang is inside the
runtime, holding the GIL released); it is deliberately leaked — daemonized,
its ticket abandoned — and the pool refuses new device work once
``max_threads`` are stuck, so a flapping attachment can't leak unboundedly.
"""
from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Callable

log = logging.getLogger("ccfd_tpu.dispatch")


class ScorerTimeout(Exception):
    """Device dispatch exceeded its deadline and no host fallback exists."""


class _Ticket:
    __slots__ = ("done", "result", "error", "abandoned")

    def __init__(self) -> None:
        self.done = threading.Event()
        self.result: Any = None
        self.error: BaseException | None = None
        self.abandoned = False  # set by the waiter on timeout


class DeviceDispatcher:
    """Run callables on worker threads with a per-call deadline.

    Workers are spawned lazily up to ``max_threads``; above the cap, calls
    queue and the deadline covers queue wait + execution, so healthy
    concurrency beyond the cap degrades to waiting — it is never mistaken
    for a wedge (only a genuine deadline expiry is). A worker that picks up
    a ticket whose waiter already gave up skips it (the work would be stale
    device churn executed after the attachment heals).
    """

    def __init__(self, max_threads: int = 4, name: str = "ccfd-dispatch"):
        self.max_threads = int(max_threads)
        self._name = name
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._lock = threading.Lock()
        self._n_threads = 0
        self._n_idle = 0
        self._seq = 0

    def _spawn_locked(self) -> None:
        self._seq += 1
        t = threading.Thread(
            target=self._worker, name=f"{self._name}-{self._seq}", daemon=True
        )
        self._n_threads += 1
        self._n_idle += 1
        t.start()

    def _worker(self) -> None:
        while True:
            ticket, fn = self._q.get()
            with self._lock:
                self._n_idle -= 1
            if ticket.abandoned:
                with self._lock:
                    self._n_idle += 1
                continue
            try:
                ticket.result = fn()
            # ccfd-lint: disable=counted-drops -- not a drop: ticket.error re-raises at the waiter in call()
            except BaseException as e:  # noqa: BLE001 - delivered to waiter
                ticket.error = e
            ticket.done.set()
            with self._lock:
                self._n_idle += 1

    def call(self, fn: Callable[[], Any], deadline_s: float) -> Any:
        """Run ``fn`` with a deadline covering queue wait + execution.
        Raises :class:`ScorerTimeout` on expiry."""
        with self._lock:
            if self._n_idle == 0 and self._n_threads < self.max_threads:
                self._spawn_locked()
        ticket = _Ticket()
        self._q.put((ticket, fn))
        if ticket.done.wait(timeout=deadline_s):
            if ticket.error is not None:
                raise ticket.error
            return ticket.result
        ticket.abandoned = True
        raise ScorerTimeout(f"device dispatch exceeded {deadline_s:.3f}s")


class WedgeMonitor:
    """Tracks whether the device attachment is believed wedged and probes for
    recovery so serving can return to the device path without manual action.

    ``probe_fn`` must be a cheap device round trip (a tiny dispatch). It runs
    through the same :class:`DeviceDispatcher` so a still-wedged attachment
    costs one sacrificial thread per probe interval at worst — and the
    dispatcher's thread cap bounds even that.
    """

    def __init__(
        self,
        dispatcher: DeviceDispatcher,
        probe_fn: Callable[[], Any],
        deadline_s: float,
        probe_interval_s: float = 10.0,
    ):
        self._dispatcher = dispatcher
        self._probe_fn = probe_fn
        self._deadline_s = float(deadline_s)
        self._probe_interval_s = float(probe_interval_s)
        self._lock = threading.Lock()
        self._wedged_since: float | None = None
        self._prober: threading.Thread | None = None
        self.on_change: Callable[[bool], None] | None = None

    @property
    def wedged(self) -> bool:
        with self._lock:
            return self._wedged_since is not None

    @property
    def wedged_for_s(self) -> float:
        with self._lock:
            if self._wedged_since is None:
                return 0.0
            return time.monotonic() - self._wedged_since

    def mark_wedged(self) -> None:
        with self._lock:
            first = self._wedged_since is None
            if first:
                self._wedged_since = time.monotonic()
            # _prober is None exactly when no prober loop will make another
            # pass: the loop only exits under this lock after nulling it
            # (an is_alive() check would race with a prober between its
            # final wedged-check and thread exit)
            start_prober = first and self._prober is None
            if start_prober:
                self._prober = threading.Thread(
                    target=self._probe_loop, name="ccfd-wedge-probe", daemon=True
                )
                self._prober.start()
        if first and self.on_change is not None:
            try:
                self.on_change(True)
            except Exception:  # noqa: BLE001 - observer must not break serving
                log.warning("wedge observer raised on mark_wedged",
                            exc_info=True)

    def _clear(self) -> None:
        with self._lock:
            was = self._wedged_since is not None
            self._wedged_since = None
        if was and self.on_change is not None:
            try:
                self.on_change(False)
            except Exception:  # noqa: BLE001 - observer must not break serving
                log.warning("wedge observer raised on clear", exc_info=True)

    def _probe_loop(self) -> None:
        while True:
            with self._lock:
                if self._wedged_since is None:
                    # exit is atomic with nulling the handle: a concurrent
                    # mark_wedged either sees _prober set (and this loop's
                    # next pass picks the new wedge up) or spawns a fresh one
                    self._prober = None
                    return
            try:
                self._dispatcher.call(self._probe_fn, self._deadline_s)
            except ScorerTimeout:
                time.sleep(self._probe_interval_s)
                continue
            # ccfd-lint: disable=counted-drops -- a failing probe is the wedged steady state, already exported via the wedge gauge; per-interval logs would spam
            except Exception:  # noqa: BLE001 - a failing probe is not recovery
                time.sleep(self._probe_interval_s)
                continue
            self._clear()
            # loop: the exit decision happens under the lock above
