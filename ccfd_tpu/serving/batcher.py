"""Dynamic request batching: concurrent predicts coalesce into one dispatch.

SURVEY.md §7 stage 2 specifies the serving shape as "request -> micro-batch
queue -> TPU", and hard part (d) is the policy: batch enough to hit 50k tx/s
without blowing the p99 <10 ms budget. The reference has no equivalent —
its Seldon pod scores each HTTP request alone, which is exactly the
per-request dispatch overhead this framework exists to amortize.

Policy (adaptive, not a fixed delay):

- The worker blocks until at least one request is queued, then drains
  whatever else is ALREADY waiting — a lone sequential client therefore
  pays zero added latency.
- If the non-blocking drain found company (a concurrency signal), the
  worker keeps collecting up to ``deadline_ms`` or ``max_batch`` — under
  load, dispatches grow toward the efficient bucket sizes instead of
  degenerating into per-request launches.
- One ``scorer.score`` call serves the whole batch; rows route back to
  their requests' futures. A scorer failure fails exactly the requests in
  that batch, never the worker.
- ``workers`` > 1 OVERLAPS dispatches: while one batch is on the wire to
  the device (which can be tens of ms through a tunneled TPU), another
  worker is already collecting and launching the next. Under continuous
  load a single worker makes every request wait for the in-flight
  dispatch *plus* its own (~2x device RTT); overlapping brings the queue
  wait back down toward one RTT and multiplies throughput by the
  pipeline depth the device can absorb. XLA dispatch is thread-safe and
  releases the GIL, so workers genuinely overlap.

This composes with the Scorer's shape bucketing: the batcher decides WHEN
to dispatch, the scorer pads the result to a compiled bucket.

Overload policy (runtime/overload.py; both knobs default OFF, preserving
the historical unbounded-queue semantics):

- ``codel`` (a :class:`~ccfd_tpu.runtime.overload.DeadlinePolicy`)
  CoDel-style drops stale requests FROM THE FRONT at dispatch-assembly
  time: a request whose queue sojourn exceeds its priority class's target
  fails with :class:`~ccfd_tpu.runtime.overload.OverloadShed` (the REST
  fronts map it to 429 + retry-after) instead of reaching the device —
  serving already-blown work at saturation just blows the SLO for
  everything queued behind it.
- ``max_queue_rows`` bounds the queue with priority-aware eviction: an
  arrival past the bound evicts queued LOWER-priority work (front first)
  to make room, or — when the arrival is itself the cheapest — is refused
  synchronously with ``OverloadShed``.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Callable

import numpy as np


class DynamicBatcher:
    def __init__(
        self,
        score_fn: Callable[[np.ndarray], np.ndarray],
        max_batch: int = 16384,
        deadline_ms: float = 2.0,
        on_dispatch: Callable[[int], None] | None = None,
        workers: int = 1,
        codel: "object | None" = None,
        max_queue_rows: int = 0,
        on_shed: Callable[[int, int], None] | None = None,
        profiler: "object | None" = None,
        profile_stage: str = "rest",
    ):
        self._score = score_fn
        # stage profiler (observability/profile.py): per coalesced
        # dispatch, feed the queue-sojourn / device-dispatch split under
        # "<profile_stage>.batcher" / "<profile_stage>.dispatch" — the
        # measured layers of the REST latency-budget ledger
        self._profiler = profiler
        self._stage_queue = f"{profile_stage}.batcher"
        self._stage_dispatch = f"{profile_stage}.dispatch"
        self.max_batch = max_batch
        self.deadline_s = max(0.0, deadline_ms) / 1e3
        self._on_dispatch = on_dispatch
        # entries: (x, future, enqueue_ts, priority)
        self._queue: list[tuple[np.ndarray, Future, float, int]] = []
        self._queued_rows = 0
        self._codel = codel
        self._max_queue_rows = int(max_queue_rows)
        self._on_shed = on_shed  # (rows, priority) per shed decision
        self._cv = threading.Condition()
        self._stats_mu = threading.Lock()  # shed_rows: updated from both
        # submit (client) threads and worker threads, with/without _cv
        self._stop = False
        self.dispatches = 0  # observability: how many TPU launches happened
        self.rows = 0
        self.shed_rows = 0
        self._threads = [
            threading.Thread(target=self._run, daemon=True, name=f"ccfd-batcher-{i}")
            for i in range(max(1, workers))
        ]
        for t in self._threads:
            t.start()

    # -- client side -------------------------------------------------------
    def submit(self, x: np.ndarray, priority: int = 1) -> "Future[np.ndarray]":
        """Enqueue a (n, F) request; the future resolves to its (n,) slice.
        Raises :class:`~ccfd_tpu.runtime.overload.OverloadShed` when the
        bounded queue refuses the request (overload admission)."""
        x = np.ascontiguousarray(x, np.float32)
        f: "Future[np.ndarray]" = Future()
        n = x.shape[0]
        shed: list[tuple[np.ndarray, Future, float, int]] = []
        with self._cv:
            if self._stop:
                raise RuntimeError("batcher is stopped")
            if (self._max_queue_rows
                    and self._queued_rows + n > self._max_queue_rows):
                if self._queued_rows == 0:
                    pass  # idle-pass (the gate's rule): a lone oversize
                    # request runs alone rather than starving forever
                else:
                    # feasibility FIRST: evicting queued serviceable work
                    # is only justified when it actually makes the
                    # arrival fit — otherwise refuse the arrival and
                    # destroy nothing
                    evictable = sum(
                        e[0].shape[0] for e in self._queue
                        if e[3] < priority)
                    if (self._queued_rows - evictable + n
                            > self._max_queue_rows):
                        self._shed_arrival(n, priority)
                    shed = self._evict_locked(n, priority)
            self._queue.append((x, f, time.perf_counter(), priority))
            self._queued_rows += n
            self._cv.notify()
        self._fail_shed(shed)
        return f

    def _shed_arrival(self, n: int, priority: int):
        """Refuse the arriving request itself (counted, synchronous)."""
        with self._stats_mu:
            self.shed_rows += n
        if self._on_shed is not None:
            self._on_shed(n, priority)
        from ccfd_tpu.runtime.overload import OverloadShed

        raise OverloadShed("serving batcher queue full")

    def _evict_locked(self, need_rows: int, priority: int):
        """Caller holds ``self._cv``. Pop queued entries of LOWER priority
        (front first — the oldest, closest to going stale anyway) until
        ``need_rows`` fit; returns the evictees for the caller to fail
        outside the lock."""
        shed = []
        i = 0
        while (self._queued_rows + need_rows > self._max_queue_rows
               and i < len(self._queue)):
            if self._queue[i][3] < priority:
                entry = self._queue.pop(i)
                self._queued_rows -= entry[0].shape[0]
                shed.append(entry)
            else:
                i += 1
        return shed

    def _fail_shed(self, shed) -> None:
        if not shed:
            return
        from ccfd_tpu.runtime.overload import OverloadShed

        for x, f, _enq, pri in shed:
            # dedicated stats lock: submit threads and batcher workers
            # both shed, and a lost += here would undercount the shed
            # accounting the SLO harness gates on
            with self._stats_mu:
                self.shed_rows += x.shape[0]
            if self._on_shed is not None:
                self._on_shed(x.shape[0], pri)
            if not f.done():
                f.set_exception(OverloadShed(
                    "shed from the serving queue for higher-priority work"))

    def score(self, x: np.ndarray, priority: int = 1) -> np.ndarray:
        """Synchronous convenience: submit + wait."""
        return self.submit(x, priority=priority).result()

    def qsize(self) -> int:
        """Requests currently queued (not yet taken by a worker) — the
        public depth surface monitoring probes read."""
        with self._cv:
            return len(self._queue)

    # -- worker ------------------------------------------------------------
    def _take_first(self) -> list:
        with self._cv:
            while not self._queue and not self._stop:
                self._cv.wait()
            batch = self._queue
            self._queue = []
            self._queued_rows = 0
            return batch

    def _drain_locked(self, room: int) -> list:
        """Caller holds self._cv. Pops queued requests that fit in ``room``;
        a request bigger than the remaining room stays queued for its own
        dispatch (merging it would make the whole batch wait for a
        multi-bucket score)."""
        take: list = []
        while self._queue and room > 0:
            x = self._queue[0][0]
            if x.shape[0] > room:
                break
            take.append(self._queue.pop(0))
            self._queued_rows -= x.shape[0]
            room -= x.shape[0]
        return take

    def _shed_stale(self, batch: list) -> list:
        """CoDel-style deadline policy at dispatch assembly: entries whose
        queue sojourn exceeds their class target drop FROM THE FRONT (the
        queue is FIFO, so stale entries are the head) and fail with
        OverloadShed; fresh work behind them still makes the dispatch."""
        if self._codel is None or not batch:
            return batch
        now = time.perf_counter()
        # head-first cheap check: fresh head == fresh batch
        if now - batch[0][2] <= self._codel.target_s:
            return batch
        kept: list = []
        shed: list = []
        for entry in batch:
            if self._codel.should_drop(now - entry[2], entry[3]):
                shed.append(entry)
            else:
                kept.append(entry)
        self._fail_shed(shed)
        return kept

    def _run(self) -> None:
        while True:
            batch = self._take_first()
            if self._stop and not batch:
                return
            size = sum(x.shape[0] for x, _f, _e, _p in batch)
            # company in the queue at grab time = concurrency: keep
            # collecting toward the deadline. Lone request: dispatch now.
            if len(batch) > 1 and self.deadline_s > 0:
                deadline = time.perf_counter() + self.deadline_s
                # grace: how long to wait for the NEXT arrival before
                # giving up. Waiting out the whole deadline after arrivals
                # dry up just parks every merged request for the residual —
                # with a bounded client pool the queue drains in one sweep
                # and nothing else is coming for a full round trip.
                grace = self.deadline_s / 8.0
                with self._cv:
                    while size < self.max_batch and not self._stop:
                        more = self._drain_locked(self.max_batch - size)
                        if more:
                            batch.extend(more)
                            size += sum(x.shape[0] for x, _f, _e, _p in more)
                            continue
                        if self._queue:
                            break  # head doesn't fit: give it its own dispatch
                        remaining = deadline - time.perf_counter()
                        # wait wakes on submit's notify, else the grace
                        # lapses and the batch goes — no busy polling
                        if remaining <= 0 or not self._cv.wait(
                            timeout=min(grace, remaining)
                        ):
                            break
            batch = self._shed_stale(batch)
            if batch:
                self._dispatch(batch)

    def _dispatch(self, batch: list) -> None:
        xs = [x for x, _f, _e, _p in batch]
        n_rows = int(sum(x.shape[0] for x in xs))
        t0 = time.perf_counter()
        if self._profiler is not None:
            # queue sojourn up to dispatch assembly, row-weighted mean —
            # the "batcher_wait" layer of the REST budget ledger
            wait = sum((t0 - e) * x.shape[0]
                       for x, _f, e, _p in batch) / max(1, n_rows)
            self._profiler.observe(self._stage_queue, queue_s=wait,
                                   rows=n_rows)
        try:
            proba = self._score(np.concatenate(xs) if len(xs) > 1 else xs[0])
        except Exception as e:  # noqa: BLE001 - fail the batch, not the worker
            for _x, f, _e2, _p in batch:
                if not f.cancelled():
                    f.set_exception(e)
            return
        if self._profiler is not None:
            self._profiler.observe(
                self._stage_dispatch,
                dispatch_s=time.perf_counter() - t0,
                batch=n_rows, rows=n_rows)
        with self._cv:  # workers share the stats; += alone would race
            self.dispatches += 1
            self.rows += n_rows
        if self._on_dispatch is not None:
            self._on_dispatch(n_rows)
        off = 0
        for x, f, _e, _p in batch:
            n = x.shape[0]
            if not f.cancelled():
                f.set_result(np.asarray(proba[off : off + n]))
            off += n

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)
        # fail anything still queued so no caller blocks forever
        with self._cv:
            leftovers = self._queue
            self._queue = []
            self._queued_rows = 0
        for _x, f, _e, _p in leftovers:
            if not f.done():
                f.set_exception(RuntimeError("batcher stopped"))
