"""Dynamic request batching: concurrent predicts coalesce into one dispatch.

SURVEY.md §7 stage 2 specifies the serving shape as "request -> micro-batch
queue -> TPU", and hard part (d) is the policy: batch enough to hit 50k tx/s
without blowing the p99 <10 ms budget. The reference has no equivalent —
its Seldon pod scores each HTTP request alone, which is exactly the
per-request dispatch overhead this framework exists to amortize.

Policy (adaptive, not a fixed delay):

- The worker blocks until at least one request is queued, then drains
  whatever else is ALREADY waiting — a lone sequential client therefore
  pays zero added latency.
- If the non-blocking drain found company (a concurrency signal), the
  worker keeps collecting up to ``deadline_ms`` or ``max_batch`` — under
  load, dispatches grow toward the efficient bucket sizes instead of
  degenerating into per-request launches.
- One ``scorer.score`` call serves the whole batch; rows route back to
  their requests' futures. A scorer failure fails exactly the requests in
  that batch, never the worker.
- ``workers`` > 1 OVERLAPS dispatches: while one batch is on the wire to
  the device (which can be tens of ms through a tunneled TPU), another
  worker is already collecting and launching the next. Under continuous
  load a single worker makes every request wait for the in-flight
  dispatch *plus* its own (~2x device RTT); overlapping brings the queue
  wait back down toward one RTT and multiplies throughput by the
  pipeline depth the device can absorb. XLA dispatch is thread-safe and
  releases the GIL, so workers genuinely overlap.

This composes with the Scorer's shape bucketing: the batcher decides WHEN
to dispatch, the scorer pads the result to a compiled bucket.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Callable

import numpy as np


class DynamicBatcher:
    def __init__(
        self,
        score_fn: Callable[[np.ndarray], np.ndarray],
        max_batch: int = 16384,
        deadline_ms: float = 2.0,
        on_dispatch: Callable[[int], None] | None = None,
        workers: int = 1,
    ):
        self._score = score_fn
        self.max_batch = max_batch
        self.deadline_s = max(0.0, deadline_ms) / 1e3
        self._on_dispatch = on_dispatch
        self._queue: list[tuple[np.ndarray, Future]] = []
        self._cv = threading.Condition()
        self._stop = False
        self.dispatches = 0  # observability: how many TPU launches happened
        self.rows = 0
        self._threads = [
            threading.Thread(target=self._run, daemon=True, name=f"ccfd-batcher-{i}")
            for i in range(max(1, workers))
        ]
        for t in self._threads:
            t.start()

    # -- client side -------------------------------------------------------
    def submit(self, x: np.ndarray) -> "Future[np.ndarray]":
        """Enqueue a (n, F) request; the future resolves to its (n,) slice."""
        x = np.ascontiguousarray(x, np.float32)
        f: "Future[np.ndarray]" = Future()
        with self._cv:
            if self._stop:
                raise RuntimeError("batcher is stopped")
            self._queue.append((x, f))
            self._cv.notify()
        return f

    def score(self, x: np.ndarray) -> np.ndarray:
        """Synchronous convenience: submit + wait."""
        return self.submit(x).result()

    def qsize(self) -> int:
        """Requests currently queued (not yet taken by a worker) — the
        public depth surface monitoring probes read."""
        with self._cv:
            return len(self._queue)

    # -- worker ------------------------------------------------------------
    def _take_first(self) -> list[tuple[np.ndarray, Future]]:
        with self._cv:
            while not self._queue and not self._stop:
                self._cv.wait()
            batch = self._queue
            self._queue = []
            return batch

    def _drain_locked(self, room: int) -> list[tuple[np.ndarray, Future]]:
        """Caller holds self._cv. Pops queued requests that fit in ``room``;
        a request bigger than the remaining room stays queued for its own
        dispatch (merging it would make the whole batch wait for a
        multi-bucket score)."""
        take: list[tuple[np.ndarray, Future]] = []
        while self._queue and room > 0:
            x, f = self._queue[0]
            if x.shape[0] > room:
                break
            self._queue.pop(0)
            take.append((x, f))
            room -= x.shape[0]
        return take

    def _run(self) -> None:
        while True:
            batch = self._take_first()
            if self._stop and not batch:
                return
            size = sum(x.shape[0] for x, _ in batch)
            # company in the queue at grab time = concurrency: keep
            # collecting toward the deadline. Lone request: dispatch now.
            if len(batch) > 1 and self.deadline_s > 0:
                deadline = time.perf_counter() + self.deadline_s
                # grace: how long to wait for the NEXT arrival before
                # giving up. Waiting out the whole deadline after arrivals
                # dry up just parks every merged request for the residual —
                # with a bounded client pool the queue drains in one sweep
                # and nothing else is coming for a full round trip.
                grace = self.deadline_s / 8.0
                with self._cv:
                    while size < self.max_batch and not self._stop:
                        more = self._drain_locked(self.max_batch - size)
                        if more:
                            batch.extend(more)
                            size += sum(x.shape[0] for x, _ in more)
                            continue
                        if self._queue:
                            break  # head doesn't fit: give it its own dispatch
                        remaining = deadline - time.perf_counter()
                        # wait wakes on submit's notify, else the grace
                        # lapses and the batch goes — no busy polling
                        if remaining <= 0 or not self._cv.wait(
                            timeout=min(grace, remaining)
                        ):
                            break
            self._dispatch(batch)

    def _dispatch(self, batch: list[tuple[np.ndarray, Future]]) -> None:
        xs = [x for x, _ in batch]
        try:
            proba = self._score(np.concatenate(xs) if len(xs) > 1 else xs[0])
        except Exception as e:  # noqa: BLE001 - fail the batch, not the worker
            for _, f in batch:
                if not f.cancelled():
                    f.set_exception(e)
            return
        n_rows = int(sum(x.shape[0] for x in xs))
        with self._cv:  # workers share the stats; += alone would race
            self.dispatches += 1
            self.rows += n_rows
        if self._on_dispatch is not None:
            self._on_dispatch(n_rows)
        off = 0
        for x, f in batch:
            n = x.shape[0]
            if not f.cancelled():
                f.set_result(np.asarray(proba[off : off + n]))
            off += n

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)
        # fail anything still queued so no caller blocks forever
        with self._cv:
            leftovers = self._queue
            self._queue = []
        for _, f in leftovers:
            if not f.done():
                f.set_exception(RuntimeError("batcher stopped"))
