"""Python half of the native HTTP serving front (native/httpfront.cpp).

The C++ side owns sockets, HTTP parsing, auth, canonical-payload decode,
and response formatting; this module runs the only parts that need
Python — scoring and the rare non-canonical routes:

- N scorer threads: ``ccfd_front_take`` hands over MANY requests as ONE
  concatenated float32 row block (the C++ queue IS the dynamic batcher);
  one ``scorer.score`` per block; ``ccfd_front_respond`` fans results
  back out per request. N > 1 overlaps device round trips exactly like
  DynamicBatcher's workers.
- one misc thread: GET /prometheus, health, and payloads the native
  decoder bailed on (names remapping, ragged rows, bad JSON) flow
  through the SAME ``PredictionServer._http_handler`` routing as the
  pure-Python server — identical contract, different fast path.

Metrics parity with serving/server.py: per-request latency lands in the
seldon histogram using the C++ enqueue timestamp (CLOCK_MONOTONIC, the
same clock as time.monotonic), request counters by code, and the
ModelPrediction gauges from the last scored row. C++-side 401s are
reconciled into the counter at scrape time.
"""

from __future__ import annotations

import ctypes
import json
import logging
import threading
import time

import numpy as np

from ccfd_tpu.native import _load
from ccfd_tpu.serving.dispatch import ScorerTimeout


def extract_dense_model(spec_name: str, params) -> tuple | None:
    """Flatten a scorer's host params into the C++ front's dense layout.

    Returns ``(dims, weights, biases, mean, inv_std)`` — weights per layer
    TRANSPOSED to (out x in) row-major and concatenated, so each output
    neuron's weights are contiguous for the C++ inner loop — or None when
    the model has no dense form (e.g. trees), in which case the front
    keeps routing predict requests to the Python takers.
    """
    try:
        if spec_name == "mlp":
            layers = params["layers"]
            dims = [int(np.asarray(layers[0]["w"]).shape[0])] + [
                int(np.asarray(layer["w"]).shape[1]) for layer in layers
            ]
            weights = np.concatenate(
                [np.asarray(layer["w"], np.float32).T.ravel() for layer in layers]
            )
            biases = np.concatenate(
                [np.asarray(layer["b"], np.float32).ravel() for layer in layers]
            )
            mean = np.asarray(params["norm"]["mu"], np.float32)
            sigma = np.asarray(params["norm"]["sigma"], np.float32)
            inv_std = np.where(sigma == 0.0, 1.0, 1.0 / sigma).astype(np.float32)
            return dims, weights, biases, mean, inv_std
        if spec_name in ("logreg", "modelfull"):
            w = np.asarray(params["w"], np.float32).reshape(-1)
            b = np.asarray(params["b"], np.float32).reshape(-1)[:1]
            # standardizer already folded into (w, b) by from_sklearn/fit
            return [int(w.shape[0]), 1], w.copy(), b.copy(), None, None
    except (KeyError, TypeError, IndexError, ValueError):
        return None
    return None


def extract_q8_model(params) -> tuple | None:
    """Flatten int8-quantized MLP params (ops/quant.py layout) into the
    C++ front's q8 layout: weights are the int8 VALUES widened to f32
    (the front's f32 SIMD dot of <=2^24-magnitude integers IS the int32
    accumulate), transposed (out x in) row-major and concatenated;
    scales/biases per-output concatenated; mu/sigma RAW (the front
    divides by sigma for bit parity with apply_numpy)."""
    try:
        layers = params["layers"]
        if "wq" not in layers[0]:
            return None
        dims = [int(np.asarray(layers[0]["wq"]).shape[0])] + [
            int(np.asarray(layer["wq"]).shape[1]) for layer in layers
        ]
        weights = np.concatenate(
            [np.asarray(layer["wq"], np.float32).T.ravel() for layer in layers]
        )
        scales = np.concatenate(
            [np.asarray(layer["scale"], np.float32).ravel() for layer in layers]
        )
        biases = np.concatenate(
            [np.asarray(layer["b"], np.float32).ravel() for layer in layers]
        )
        mean = np.asarray(params["norm"]["mu"], np.float32)
        sigma = np.asarray(params["norm"]["sigma"], np.float32)
        return dims, weights, scales, biases, mean, sigma
    except (KeyError, TypeError, IndexError, ValueError):
        return None


def extract_tree_model(params) -> tuple | None:
    """Flatten a tree-ensemble param tree (models/trees.py dense embedding)
    into the C++ front's layout: ``(n_trees, depth, feat, thr, leaf, base)``
    with feat/thr/leaf as flat contiguous arrays in heap order."""
    from ccfd_tpu.models import trees

    try:
        feat = np.ascontiguousarray(params["feature"], np.int32)
        thr = np.ascontiguousarray(params["threshold"], np.float32)
        leaf = np.ascontiguousarray(params["leaf"], np.float32)
        n_trees = int(leaf.shape[0])
        depth = trees.depth_of(params)
        if feat.shape != (n_trees, trees.num_internal(depth)) or \
                thr.shape != feat.shape:
            return None
        return n_trees, depth, feat, thr, leaf, float(params["base"])
    except (KeyError, TypeError, IndexError, ValueError):
        return None


class NativeFront:
    # In-IO-thread scoring cap, SEPARATE from the scorer's host-tier
    # threshold: the epoll thread serializes all connections, so an inline
    # score must stay well under a millisecond (~512 rows at ~1.4 us/row)
    # or one big request head-of-line blocks every other client. Requests
    # between this cap and host_tier_rows still avoid the device — they
    # flow to the Python takers where scorer.score applies the numpy host
    # tier on a worker thread.
    INLINE_MAX_ROWS = 512

    def __init__(
        self,
        server,  # PredictionServer (duck-typed: scorer, cfg, registry, ...)
        max_batch_rows: int = 16384,
        max_reqs_per_take: int = 1024,
    ):
        self._server = server
        self._lib = _load()
        if self._lib is None:
            raise RuntimeError("native toolchain unavailable")
        self._handle = None
        self._threads: list[threading.Thread] = []
        self._stopping = threading.Event()
        self._max_rows = max_batch_rows
        self._max_reqs = max_reqs_per_take
        self._auth_fail_synced = 0
        self.server_address = ("0.0.0.0", 0)
        # host-model scrape-fold state (see _sync_native_counters)
        self._n_buckets = 0
        self._host_synced_counts: np.ndarray | None = None
        self._host_synced_sums = np.zeros(2, np.float64)
        self._host_synced_n = 0
        self._gauge_synced_ms = 0.0
        self._swap_listener = None
        # serializes host-model pushes (swap_params listener thread) against
        # stop(): a push in flight must complete before the handle is torn
        # down, or ctypes hands C++ a null/freed Front*
        self._push_lock = threading.Lock()
        self.host_model_active = False
        # computed once at install (re-parsing the env per swap-push would
        # spam the malformed-value warning at swap frequency)
        self._inline_cap_cached: int | None = None

    # -- lifecycle ---------------------------------------------------------
    def start(self, port: int = 0, host: str = "0.0.0.0") -> int:
        srv = self._server
        port_out = ctypes.c_int(0)
        handle = self._lib.ccfd_front_create(
            (host or "0.0.0.0").encode(),
            int(port),
            srv.scorer.num_features,
            (srv.cfg.seldon_token or "").encode(),
            ctypes.byref(port_out),
        )
        if not handle:
            raise OSError(f"native front failed to bind {host}:{port}")
        self._handle = handle
        self.server_address = (host or "0.0.0.0", int(port_out.value))
        workers = max(1, getattr(srv.cfg, "batch_workers", 2))
        for i in range(workers):
            t = threading.Thread(
                target=self._score_loop, daemon=True, name=f"ccfd-front-score-{i}"
            )
            t.start()
            self._threads.append(t)
        t = threading.Thread(
            target=self._misc_loop, daemon=True, name="ccfd-front-misc"
        )
        t.start()
        self._threads.append(t)
        self._install_host_model()
        return int(port_out.value)

    # -- in-front host-tier model ------------------------------------------
    def _inline_rows_cap(self) -> int:
        """Row cap for in-IO-thread scoring. The host latency TIER's
        threshold (measured device RTT vs numpy rate) governs where it is
        armed; where it is off (CPU backends auto-disable it — there is no
        attachment RTT to hide), the C++ SIMD forward still beats a jax
        dispatch for small requests (~1.4 us/row vs hundreds of us of
        dispatch+queue overhead), so the front keeps a default 256-row cap
        there. CCFD_INLINE_ROWS overrides; 0 disables."""
        import os

        if self._inline_cap_cached is not None:
            return self._inline_cap_cached
        env = os.environ.get("CCFD_INLINE_ROWS", "").strip()
        if env:
            try:
                self._inline_cap_cached = min(int(env), self.INLINE_MAX_ROWS)
                return self._inline_cap_cached  # explicit wins
            except ValueError:
                import sys

                print(
                    f"[native-front] ignoring non-integer "
                    f"CCFD_INLINE_ROWS={env!r}",
                    file=sys.stderr,
                )
        htr = int(self._server.scorer.host_tier_rows)
        if htr > 0:
            cap = htr
        else:
            import jax

            # tier auto-off on cpu (no attachment RTT to hide) still wants
            # in-front scoring; tier explicitly off on an accelerator is an
            # operator choice — respect it
            cap = 256 if jax.default_backend() == "cpu" else 0
        self._inline_cap_cached = min(cap, self.INLINE_MAX_ROWS)
        return self._inline_cap_cached

    def _install_host_model(self) -> None:
        """Push the scorer's host params into the C++ front so small
        canonical requests score in the IO thread with ZERO Python handoffs
        (the decisive path on a small serving host: the queue round trip
        costs more in context switches than the forward itself). Re-pushed
        on every ``swap_params`` so online retrain reaches the front."""
        srv = self._server
        if self._inline_rows_cap() <= 0:
            return
        host_params = getattr(srv.scorer, "_host_params", None)
        if host_params is None:
            return
        h = srv._h_latency
        ubs = (ctypes.c_double * len(h.buckets))(*h.buckets)
        self._n_buckets = len(h.buckets)
        self._lib.ccfd_front_set_latency_buckets(
            self._handle, ubs, len(h.buckets)
        )
        self._host_synced_counts = np.zeros((2, self._n_buckets), np.int64)
        self._host_synced_sums = np.zeros(2, np.float64)
        if self._push_host_model(host_params):
            self._swap_listener = self._push_host_model
            srv.scorer.add_swap_listener(self._swap_listener)

    def _push_host_model(self, host_params) -> bool:
        spec_name = self._server.scorer.spec.name
        if spec_name == "gbt":
            extracted = extract_tree_model(host_params)
            pusher = self._push_host_trees_locked
        elif spec_name == "mlp_q8":
            extracted = extract_q8_model(host_params)
            pusher = self._push_host_q8_locked
        else:
            extracted = extract_dense_model(spec_name, host_params)
            pusher = self._push_host_model_locked
        if extracted is None:
            return False
        # one guarded call for every model family: the stop()-vs-push
        # interlock (handle/stopping re-check under the lock) must not be
        # duplicated per branch
        with self._push_lock:
            if self._handle is None or self._stopping.is_set():
                return False
            return pusher(extracted)

    def _gauge_cols(self):
        from ccfd_tpu.serving.server import _AMOUNT_COL, _V10_COL, _V17_COL

        return (ctypes.c_int * 3)(_AMOUNT_COL, _V17_COL, _V10_COL)

    def _push_host_trees_locked(self, trees) -> bool:
        n_trees, depth, feat, thr, leaf, base = trees
        fp = ctypes.POINTER(ctypes.c_float)
        self._lib.ccfd_front_set_host_trees(
            self._handle,
            n_trees,
            depth,
            feat.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            thr.ctypes.data_as(fp),
            leaf.ctypes.data_as(fp),
            base,
            self._inline_rows_cap(),
            self._server.scorer.spec.name.encode(),
            self._gauge_cols(),
        )
        self.host_model_active = True
        return True

    def _push_host_model_locked(self, extracted) -> bool:
        dims, weights, biases, mean, inv_std = extracted

        dims_c = (ctypes.c_int * len(dims))(*dims)
        gcols = self._gauge_cols()
        # locals keep the arrays alive across the ctypes call
        w = np.ascontiguousarray(weights, np.float32)
        b = np.ascontiguousarray(biases, np.float32)
        m = None if mean is None else np.ascontiguousarray(mean, np.float32)
        s = None if inv_std is None else np.ascontiguousarray(inv_std, np.float32)
        fp = ctypes.POINTER(ctypes.c_float)
        self._lib.ccfd_front_set_host_model(
            self._handle,
            len(dims) - 1,
            dims_c,
            w.ctypes.data_as(fp),
            b.ctypes.data_as(fp),
            None if m is None else m.ctypes.data_as(fp),
            None if s is None else s.ctypes.data_as(fp),
            self._inline_rows_cap(),
            self._server.scorer.spec.name.encode(),
            gcols,
        )
        self.host_model_active = True
        return True

    def _push_host_q8_locked(self, extracted) -> bool:
        if not hasattr(self._lib, "ccfd_front_set_host_q8_model"):
            return False  # pre-q8 shipped .so: requests flow to Python takers
        dims, weights, scales, biases, mean, sigma = extracted
        dims_c = (ctypes.c_int * len(dims))(*dims)
        gcols = self._gauge_cols()
        # locals keep the arrays alive across the ctypes call
        w = np.ascontiguousarray(weights, np.float32)
        sc = np.ascontiguousarray(scales, np.float32)
        b = np.ascontiguousarray(biases, np.float32)
        m = np.ascontiguousarray(mean, np.float32)
        sg = np.ascontiguousarray(sigma, np.float32)
        fp = ctypes.POINTER(ctypes.c_float)
        self._lib.ccfd_front_set_host_q8_model(
            self._handle,
            len(dims) - 1,
            dims_c,
            w.ctypes.data_as(fp),
            sc.ctypes.data_as(fp),
            b.ctypes.data_as(fp),
            m.ctypes.data_as(fp),
            sg.ctypes.data_as(fp),
            self._inline_rows_cap(),
            self._server.scorer.spec.name.encode(),
            gcols,
        )
        self.host_model_active = True
        return True

    def stop(self) -> None:
        if self._handle is None:
            return
        if self._swap_listener is not None:
            self._server.scorer.remove_swap_listener(self._swap_listener)
            self._swap_listener = None
        self._stopping.set()
        # barrier: a swap-listener push snapshotted before the removal
        # above may still be inside the ctypes call — wait it out before
        # tearing the handle down (it re-checks _stopping under this lock)
        with self._push_lock:
            pass
        # stop: wakes takers (-1) + joins the C++ IO thread; the handle
        # stays VALID until every Python worker that may be inside
        # take()/take_misc() has joined — only then destroy frees it
        self._lib.ccfd_front_stop(self._handle)
        for t in self._threads:
            t.join(timeout=10.0)
        still_alive = [t for t in self._threads if t.is_alive()]
        self._threads = []
        if not still_alive:
            self._lib.ccfd_front_destroy(self._handle)
        # else: a worker is wedged inside a device dispatch (e.g. a stuck
        # accelerator tunnel) and may still touch the handle — LEAK the
        # Front rather than free memory a live thread will poke
        self._handle = None

    # -- predict hot path --------------------------------------------------
    def _score_loop(self) -> None:
        srv = self._server
        nf = srv.scorer.num_features
        rows_buf = np.empty((self._max_rows, nf), np.float32)
        rows_ptr = rows_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        meta = (ctypes.c_int * (3 * self._max_reqs))()
        enq = (ctypes.c_double * self._max_reqs)()
        model = srv.scorer.spec.name.encode()
        while not self._stopping.is_set():
            handle = self._handle
            if handle is None:
                return
            n_reqs = self._lib.ccfd_front_take(
                handle, rows_ptr, self._max_rows, meta, enq, self._max_reqs, 200
            )
            if n_reqs <= 0:
                if n_reqs < 0:
                    return  # stopping
                continue
            ids = (ctypes.c_int * n_reqs)()
            counts = (ctypes.c_int * n_reqs)()
            tags = [0] * n_reqs
            total = 0
            for i in range(n_reqs):
                ids[i] = meta[3 * i]
                counts[i] = meta[3 * i + 1]
                tags[i] = meta[3 * i + 2]
                total += meta[3 * i + 1]
            # overload admission (runtime/overload.py): the C++ queue does
            # not forward headers, so native-path requests admit at NORMAL
            # priority, request-atomically from the front of the block;
            # the refused tail gets an explicit 429 + retry-after hint in
            # the body. The reserve is released after the respond below.
            gate = getattr(srv, "admission", None)
            admitted_rows = total
            if gate is not None:
                n_admit = 0
                admitted_rows = 0
                for i in range(n_reqs):
                    if not gate.try_admit(counts[i]):
                        break
                    admitted_rows += counts[i]
                    n_admit += 1
                if n_admit < n_reqs:
                    rej = json.dumps({
                        "error": "overloaded",
                        "retry_after_s": round(gate.retry_after_s, 3),
                    }).encode()
                    for i in range(n_admit, n_reqs):
                        self._lib.ccfd_front_respond_misc(
                            handle, ids[i], 429, b"application/json",
                            rej, len(rej),
                        )
                        srv._c_requests.inc(labels={"code": "429"})
                    n_reqs = n_admit
                    total = admitted_rows
                    if n_reqs == 0:
                        continue
            x = rows_buf[:total]
            t_sc = time.monotonic()
            try:
                proba = np.ascontiguousarray(
                    np.asarray(srv.scorer.score(x)), np.float32
                )
            except ScorerTimeout as e:
                # wedged device, no host fallback: bounded 503 (server-side
                # SELDON_TIMEOUT) instead of a taker thread stuck forever
                err = json.dumps({"error": f"scoring unavailable: {e}"}).encode()
                for i in range(n_reqs):
                    self._lib.ccfd_front_respond_misc(
                        handle, ids[i], 503, b"application/json", err, len(err)
                    )
                    srv._c_requests.inc(labels={"code": "503"})
                if gate is not None:
                    gate.release(admitted_rows)
                continue
            except Exception:  # noqa: BLE001 - fail the requests, not the loop
                err = b'{"error": "scoring failed"}'
                for i in range(n_reqs):
                    self._lib.ccfd_front_respond_misc(
                        handle, ids[i], 500, b"application/json", err, len(err)
                    )
                    srv._c_requests.inc(labels={"code": "500"})
                if gate is not None:
                    gate.release(admitted_rows)
                continue
            if gate is not None:
                gate.release(admitted_rows)
                gate.observe(time.monotonic() - t_sc)
            self._lib.ccfd_front_respond(
                handle, ids, counts, n_reqs,
                proba.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), model,
            )
            # metrics parity with the Python server path (same endpoint
            # labels the Python transport records)
            now_ms = time.monotonic() * 1e3
            for i in range(n_reqs):
                srv._h_latency.observe(
                    max(0.0, (now_ms - enq[i]) / 1e3),
                    labels={"endpoint": "/predict" if tags[i]
                            else "/api/v0.1/predictions"},
                )
            srv._c_requests.inc(n_reqs, labels={"code": "200"})
            if total:
                srv._g_proba.set(float(proba[total - 1]))
                from ccfd_tpu.serving.server import _AMOUNT_COL, _V10_COL, _V17_COL

                srv._g_amount.set(float(x[total - 1, _AMOUNT_COL]))
                srv._g_v17.set(float(x[total - 1, _V17_COL]))
                srv._g_v10.set(float(x[total - 1, _V10_COL]))
                srv._gauges_set_ms = time.monotonic() * 1e3

    # -- everything else ---------------------------------------------------
    def _misc_loop(self) -> None:
        srv = self._server
        method_buf = ctypes.create_string_buffer(16)
        path_buf = ctypes.create_string_buffer(512)
        body_ptr = ctypes.c_void_p()
        body_len = ctypes.c_int(0)
        # C++ validated the bearer token before queueing, but it does not
        # forward headers; re-synthesize the authorization the Python
        # routing re-checks so valid requests don't double-401
        auth_hdr = {}
        if srv.cfg.seldon_token:
            auth_hdr = {b"authorization": f"Bearer {srv.cfg.seldon_token}".encode()}
        while not self._stopping.is_set():
            handle = self._handle
            if handle is None:
                return
            req_id = self._lib.ccfd_front_take_misc(
                handle, method_buf, 16, path_buf, 512,
                ctypes.byref(body_ptr), ctypes.byref(body_len), 200,
            )
            if req_id < 0:
                return
            if req_id == 0:
                continue
            body = ctypes.string_at(body_ptr, body_len.value)
            self._lib.ccfd_front_free(body_ptr)
            method = method_buf.value.decode("latin-1")
            path = path_buf.value.decode("latin-1")
            if path in ("/prometheus", "/metrics"):
                self._sync_native_counters(handle)
            try:
                res = srv._http_handler(method, path, auth_hdr, body)
                # 3-tuple, or 4-tuple with extra response headers (429
                # Retry-After); the C++ responder has no header channel,
                # so the extra headers ride only in the JSON body here
                status, ctype, resp = res[0], res[1], res[2]
            except Exception:  # noqa: BLE001 - fail the request, not the loop
                logging.getLogger("ccfd_tpu.native_front").warning(
                    "misc handler raised for %s %s; answered 500",
                    method, path, exc_info=True)
                status, ctype, resp = 500, "text/plain", b"internal error"
            self._lib.ccfd_front_respond_misc(
                handle, req_id, status, ctype.encode(), resp, len(resp)
            )

    def _sync_native_counters(self, handle) -> None:
        """Fold C++-side counts into the registry before a scrape: 401s,
        plus everything the in-front host model scored without touching
        Python — request counts, the seldon latency histogram (bucket
        layout pushed at install matches 1:1), and the ModelPrediction
        gauges from the last host-scored row."""
        srv = self._server
        stats = (ctypes.c_long * 4)()
        self._lib.ccfd_front_stats(handle, stats)
        delta = int(stats[3]) - self._auth_fail_synced
        if delta > 0:
            srv._c_requests.inc(delta, labels={"code": "401"})
            self._auth_fail_synced += delta

        if self._host_synced_counts is None:
            return
        nb = self._n_buckets
        counts = (ctypes.c_long * (2 * nb))()
        sums = (ctypes.c_double * 2)()
        gauges = (ctypes.c_float * 4)()
        gauge_ms = ctypes.c_double(0.0)
        n_host = int(
            self._lib.ccfd_front_host_stats(
                handle, counts, sums, gauges, ctypes.byref(gauge_ms)
            )
        )
        d_n = n_host - self._host_synced_n
        if d_n > 0:
            srv._c_requests.inc(d_n, labels={"code": "200"})
            self._host_synced_n = n_host
        # as_array derives the dtype from the ctypes type: c_long is 8 bytes
        # on LP64 but 4 on other ABIs, so a hardcoded int64 would misparse
        cur = np.ctypeslib.as_array(counts).astype(np.int64).reshape(2, nb)
        cur_sums = np.ctypeslib.as_array(sums).astype(np.float64)
        endpoints = ("/api/v0.1/predictions", "/predict")
        for tag in (0, 1):
            d_counts = cur[tag] - self._host_synced_counts[tag]
            d_sum = cur_sums[tag] - self._host_synced_sums[tag]
            if d_counts.any() or d_sum:
                srv._h_latency.merge_counts(
                    d_counts.tolist(), float(d_sum),
                    labels={"endpoint": endpoints[tag]},
                )
        self._host_synced_counts = cur
        self._host_synced_sums = cur_sums
        # the "last scored" gauges must reflect whichever path scored most
        # recently: fold the C++ values only when they are BOTH new since
        # the last fold AND newer than the Python path's last write (same
        # CLOCK_MONOTONIC as time.monotonic, ms)
        host_ms = float(gauge_ms.value)
        if host_ms > self._gauge_synced_ms and host_ms > getattr(
            srv, "_gauges_set_ms", 0.0
        ):
            self._gauge_synced_ms = host_ms
            srv._g_proba.set(float(gauges[0]))
            srv._g_amount.set(float(gauges[1]))
            srv._g_v17.set(float(gauges[2]))
            srv._g_v10.set(float(gauges[3]))
