"""Compiled TPU scorer: fixed-shape bucketed dispatch + hot-swappable params.

This replaces the reference's Seldon-wrapped CPU model container
(reference deploy/model/modelfull.json:18-52) as the prediction hop. Design
follows the latency plan in SURVEY.md §7 "hard parts":

- **Fixed batch shapes.** XLA compiles one executable per input shape; a
  streaming workload with ragged batch sizes would re-trace constantly. The
  scorer pads every request batch up to a configured bucket
  (CCFD_BATCH_SIZES) so steady state reuses a handful of cached executables.
- **Warmup.** ``warmup()`` runs every bucket once so no request pays the
  compile cost.
- **Double-buffered params.** Online retrain (BASELINE.json configs[4])
  must not pause serving: ``swap_params`` device-puts the new pytree and
  swaps a reference atomically between dispatches — in-flight calls keep the
  old buffers alive, the next call picks up the new ones.
- **Mesh-sharded dispatch.** The reference scales serving by k8s replicas +
  Kafka partitioning (reference deploy/frauddetection_cr.yaml:76,
  router.yaml:32); the TPU analog is ONE scorer whose batch shards over the
  ``"data"`` axis of a ``jax.sharding.Mesh`` (SURVEY.md §7 stage 6).
  ``Scorer(mesh=...)`` keeps the exact same bucketing/warmup/swap surface:
  buckets round up to multiples of the data-axis size, inputs are
  device_put with a NamedSharding so each chip receives only its rows, and
  params ride replicated (default) or megatron-sharded over the ``"model"``
  axis (``param_partition="model"``, layout in ccfd_tpu/parallel/sharding.py).
  The fused Pallas kernel composes via ``shard_map``: every chip runs the
  single-chip kernel on its shard — collectives only appear if the model
  axis is used, and XLA schedules those.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np

from ccfd_tpu.data.ccfd import NUM_FEATURES
from ccfd_tpu.models.registry import ModelSpec, get_model
from ccfd_tpu.runtime.faults import device_seam

_DTYPES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
}


def _host_cast(a: Any) -> np.ndarray:
    """Host copy of one param leaf for the numpy tier: floating leaves go to
    f32, integer leaves (tree feature indices) keep an integer dtype — a
    uniform f32 cast would turn gather indices into floats and crash
    ``apply_numpy`` for the tree family."""
    a = np.asarray(a)
    if np.issubdtype(a.dtype, np.floating):
        return np.asarray(a, np.float32)
    return a


class Scorer:
    def __init__(
        self,
        model_name: str = "mlp",
        params: Any = None,
        batch_sizes: Sequence[int] = (16, 128, 1024, 4096, 16384),
        compute_dtype: str = "bfloat16",
        num_features: int = NUM_FEATURES,
        seed: int = 0,
        use_fused: bool | None = None,
        mesh: Any = None,
        param_partition: str = "replicated",
        host_tier_rows: int | None = None,
        dispatch_deadline_ms: float | None = None,
        telemetry: Any = None,
        partitioner: Any = None,
    ):
        self.spec: ModelSpec = get_model(model_name)
        self.num_features = num_features
        # first-class partitioning layer (parallel/partition.py): when
        # given, the partitioner owns every sharding decision — batch over
        # its data axis, params per its layout (replicated or rule-table
        # SPMD), and param publishes route through its pause-barrier
        # publish path. The bare ``mesh=`` form keeps the historical
        # hand-rolled layout (the dryrun's shape).
        self.partitioner = partitioner
        if partitioner is not None:
            mesh = partitioner.mesh
        self.mesh = mesh
        # device telemetry plane (observability/device.py): when armed,
        # every staging put on the dispatch path is timed + byte-counted
        # (ccfd_h2d_bytes_total / ccfd_h2d_seconds — the measured numbers
        # the BudgetLedger's h2d layer reads). None resolves through the
        # module default so harnesses (bench) arm scorers built deep
        # inside helpers; the operator passes its instance explicitly.
        if telemetry is None:
            from ccfd_tpu.observability import device as _device

            telemetry = _device.get_default()
        self.telemetry = telemetry
        if param_partition not in ("replicated", "model"):
            raise ValueError(f"unknown param_partition {param_partition!r}")
        if param_partition == "model" and model_name != "mlp":
            # a silent fallback to replication would hand a caller who needs
            # the sharded layout (model too big replicated) an OOM later
            raise ValueError(
                f"param_partition='model' has a layout only for 'mlp', "
                f"not {model_name!r}"
            )
        self._param_partition = param_partition
        self._batch_sharding = None
        self._param_sharding = None
        if partitioner is not None:
            self._data_size = partitioner.data_size
            batch_sizes = {partitioner.round_batch(b) for b in batch_sizes}
            self._batch_sharding = partitioner.batch_sharding
            self._out_sharding = partitioner.out_sharding
        elif mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ccfd_tpu.parallel.mesh import DATA_AXIS

            self._data_size = mesh.shape[DATA_AXIS]
            # every bucket must split evenly over the data axis
            batch_sizes = {
                -(-b // self._data_size) * self._data_size for b in batch_sizes
            }
            self._batch_sharding = NamedSharding(mesh, P(DATA_AXIS, None))
            self._out_sharding = NamedSharding(mesh, P(DATA_AXIS))
        self.batch_sizes = tuple(sorted(batch_sizes))
        self._params = params if params is not None else self.spec.init(
            jax.random.PRNGKey(seed)
        )
        if partitioner is not None:
            self._param_sharding = partitioner.param_sharding(self._params)
            self._params = jax.device_put(self._params, self._param_sharding)
        elif mesh is not None:
            from ccfd_tpu.parallel import sharding as shardlib

            if param_partition == "model":
                self._param_sharding = shardlib.mlp_param_spec(self._params, mesh)
            else:
                rep = shardlib.replicated(mesh)
                self._param_sharding = jax.tree.map(lambda _: rep, self._params)
            self._params = jax.device_put(self._params, self._param_sharding)
        else:
            self._params = jax.device_put(self._params)
        # swap-vs-dispatch publish gate (parallel/partition.py
        # PublishGate): armed by the operator once the router pool exists;
        # every swap_params then quiesces the pool's in-flight sharded
        # dispatches at a batch boundary before re-laying params
        self._swap_gate: Any = None
        self._lock = threading.Lock()
        # per-bucket dispatch tally for the executable inventory (PR 10):
        # on a mesh every dispatch is one SPMD launch spanning all
        # devices, so per-device counts read straight off this grid
        self._dispatch_counts: dict[int, int] = {}
        dtype = _DTYPES.get(compute_dtype, jnp.float32)
        # models without a dtype knob (e.g. trees) take (params, x) only
        import inspect

        sig = inspect.signature(self.spec.apply)
        if "compute_dtype" in sig.parameters:
            self._apply = lambda p, x: self.spec.apply(p, x, compute_dtype=dtype)
        else:
            self._apply = self.spec.apply
        if mesh is not None:
            # constrain the output to stay data-sharded: the partitioner
            # must not all-gather probabilities onto one chip before D2H
            self._apply = jax.jit(self._apply, out_shardings=self._out_sharding)

        # Pallas fused path: the whole MLP in one kernel, weights VMEM-
        # resident (ccfd_tpu/ops/fused_mlp.py). Auto-on for the flagship MLP
        # in reduced precision; params are re-folded on every swap so online
        # retrain keeps working. ``use_fused=False`` forces the XLA path.
        self._fused_params = None
        self._preq_norm = None
        self._preq_wire = False
        if use_fused is None:
            # auto only on real TPU: the CPU interpreter runs the same kernel
            # body but orders of magnitude slower (tests opt in explicitly).
            # mlp_q8 has its own int8 kernel (ops/fused_mlp_q8.py) whose
            # compute precision is fixed by quantization, so no dtype gate.
            use_fused = jax.default_backend() == "tpu" and (
                (self.spec.name == "mlp" and dtype == jnp.bfloat16)
                or self.spec.name == "mlp_q8"
            )
        # Host latency tier: when the accelerator sits behind a high-RTT
        # attachment (a tunneled TPU adds tens of ms per dispatch), a small
        # request batch is faster on the HOST in plain numpy than the wire
        # round trip — ~50us for this MLP at 16-256 rows vs a full RTT. The
        # device keeps the throughput work (bulk/pipelined scoring, big
        # buckets); requests at or under ``host_tier_rows`` score on a host
        # copy of the params. Auto-on (256 rows) for models with a numpy
        # forward when the default backend is an accelerator; 0 disables.
        # Numerical note: the host tier computes f32, the device path
        # bf16 — within ~1e-2 in probability (asserted by tests).
        self._host_tier_auto = host_tier_rows is None
        if host_tier_rows is None:
            # provisional until warmup() measures the attachment: a tunneled
            # chip (tens of ms RTT) justifies thousands of host rows, a
            # local chip only tens — ``_autotune_host_tier`` picks the real
            # crossover from measured device RTT vs measured host rate
            host_tier_rows = (
                256
                if (
                    self.spec.apply_numpy is not None
                    and mesh is None
                    and jax.default_backend() not in ("cpu",)
                )
                else 0
            )
        self.host_tier_rows = int(host_tier_rows)
        self._host_params = None
        # swap listeners: components holding a derived copy of the params
        # (e.g. the C++ serving front's in-process host model) register to
        # be re-fed on every swap_params so online retrain reaches them too.
        # Delivery is serialized under _notify_lock and ordered by a swap
        # generation so two concurrent swap_params calls can't install their
        # listeners' copies in reverse order (stale params winning).
        self._swap_listeners: list[Any] = []
        self._notify_lock = threading.Lock()
        self._swap_gen = 0
        self._swap_delivered_gen = 0
        # prepublish hooks: planes that compile executables against the
        # params (the fused decision grid) precompile against the STAGED
        # buffers here, before the flip — so the swap publishes with every
        # bucket warm, exactly like the seq variant swap. Hooks run gate-
        # free (staging side); a failing hook never blocks the publish.
        self._prepublish_hooks: list[Any] = []
        # host materializations per score_pipelined call site: the staged
        # path pays one np.asarray(done) sync per chunk; the fused decision
        # bench reads this to report host_syncs_per_batch for BOTH paths.
        self.host_syncs = 0
        # challenger slot (lifecycle/shadow.py): a second, double-buffered
        # (version, host_params) pair living NEXT TO the champion — shadow
        # and canary scoring read it via the host numpy forward, so the
        # challenger never contends for the device. Installed/cleared by
        # the lifecycle controller; swap_params does not touch it.
        self._challenger: tuple[int, Any] | None = None
        # Dispatch deadline (server-side SELDON_TIMEOUT analog,
        # /root/reference/README.md:386-393): the serving ``score`` path
        # bounds its device round trip; a wedged attachment (tunnel hang
        # inside a device sync) times out, marks the device wedged, and
        # serving continues on the host tier until a probe sees recovery.
        # None = auto: SELDON_TIMEOUT ms on accelerator backends, off on CPU
        # (no attachment to wedge) and on meshes (the dryrun/virtual path).
        if dispatch_deadline_ms is None:
            if mesh is None and jax.default_backend() not in ("cpu",):
                from ccfd_tpu.config import Config

                # env-backed Config is the single parser for both knobs;
                # callers holding a programmatic Config pass
                # cfg.scorer_dispatch_deadline_ms() instead of None
                dispatch_deadline_ms = Config.from_env().scorer_dispatch_deadline_ms()
            else:
                dispatch_deadline_ms = 0.0
        self.dispatch_deadline_s = float(dispatch_deadline_ms) / 1e3
        self._dispatcher = None
        self._wedge = None
        self.dispatch_timeouts = 0
        self.host_fallback_scores = 0
        # Host params are kept whenever the family has a host forward: the
        # latency tier routes by host_tier_rows, the wedge fallback needs
        # them armed BEFORE a wedge (they cannot be pulled from a hung
        # device later), and the C++ front's in-IO-thread model derives its
        # copy from them on every backend (its SIMD forward beats even a
        # local jax dispatch for small requests). One numpy copy of the
        # params; refreshed on every swap.
        if self.spec.apply_numpy is not None:
            self._host_params = jax.tree.map(
                _host_cast, params if params is not None else self._params
            )
        if self.host_tier_rows > 0 and self._host_params is None:
            self.host_tier_rows = 0
        if self.dispatch_deadline_s > 0:
            from ccfd_tpu.serving.dispatch import DeviceDispatcher, WedgeMonitor

            self._dispatcher = DeviceDispatcher()
            probe_rows = min(self.batch_sizes)
            probe_x = np.zeros((probe_rows, self.num_features), np.float32)
            self._wedge = WedgeMonitor(
                self._dispatcher,
                lambda: self.score_pipelined(probe_x, depth=1),
                deadline_s=self.dispatch_deadline_s,
            )
        if use_fused:
            if self.spec.name == "mlp_q8":
                from ccfd_tpu.ops import fused_mlp_q8 as fused_mod
            else:
                from ccfd_tpu.ops import fused_mlp as fused_mod

            self._fused_mod = fused_mod
            # wire dtype is the kernel's call: bf16 halves H2D bytes for
            # the bf16 kernel; the q8 kernel keeps f32 for exact parity
            # with the served XLA graph (its docstring has the numbers)
            self._fused_in_dtype = (
                ml_dtypes.bfloat16
                if fused_mod.INPUT_DTYPE == "bfloat16" else np.float32
            )
            try:
                folded = fused_mod.fold_for_kernel(self._params)
                self._fused_params = self._put_fused(folded)
                self._preq_norm = self._preq_norm_of(folded)
            except (KeyError, TypeError, ValueError):
                self._fused_params = None  # incompatible layout: XLA path
            self._fused_interpret = jax.default_backend() == "cpu"
            self._fused_sharded_cache: dict[int, Any] = {}
            # int8 wire (q8 kernel, single device): on by default — the
            # math is bit-identical and only the H2D bytes change;
            # CCFD_Q8_WIRE=f32 opts out (e.g. when the serving host's CPU,
            # not the wire, is the bottleneck). Mesh serving keeps the
            # f32 wire: the preq arrays would need their own shard_map
            # composition, unwarranted before an on-TPU number exists.
            # static capability/env flag only: whether CURRENT params
            # fold is the dynamic `preq_norm is not None` check at
            # dispatch, so a later foldable swap re-enables the wire
            self._preq_wire = (
                hasattr(fused_mod, "prequantize_rows_numpy")
                and os.environ.get("CCFD_Q8_WIRE", "int8") != "f32"
            )

    @staticmethod
    def _preq_norm_of(folded: Any) -> dict | None:
        """Host copies of the folded normalizer for the int8 wire's
        host-side requantization — the SAME arrays the kernel normalizes
        with, so there is no second zero-sigma guard to drift."""
        if not isinstance(folded, dict) or "sigma" not in folded:
            return None
        return {"mu": np.asarray(folded["mu"]),
                "sigma": np.asarray(folded["sigma"])}

    def _put_fused(self, folded: Any) -> Any:
        """Fused weights live whole in every chip's VMEM: replicate on mesh."""
        if self.mesh is None:
            return folded
        from ccfd_tpu.parallel.sharding import replicated

        return jax.device_put(folded, replicated(self.mesh))

    def _put_batch(self, chunk: np.ndarray) -> jax.Array:
        """H2D with placement: on a mesh each chip gets only its row shard.
        With the device telemetry plane armed the put is timed and byte-
        counted (the measured H2D accounting; two perf_counter reads).
        The staging seam consults the device-fault plan (runtime/faults.py
        ``put_fail``) INSIDE the put, so an injected staging failure rides
        the same path — and the same telemetry failure count — a real one
        would."""
        if self._batch_sharding is None:
            def put():
                device_seam("put")
                return jnp.asarray(chunk)
        else:
            def put():
                device_seam("put")
                return jax.device_put(chunk, self._batch_sharding)
        if self.telemetry is None:
            return put()
        from ccfd_tpu.observability.device import timed_put

        return timed_put(self.telemetry, chunk.nbytes, put)

    def _fused_apply(self, fused_params: Any, x: jax.Array) -> jax.Array:
        rows = x.shape[0] if self.mesh is None else x.shape[0] // self._data_size
        tile = self._fused_mod.fit_tile(rows)
        if self.mesh is None:
            return self._fused_mod.fused_score(
                fused_params, x, tile=tile, interpret=self._fused_interpret
            )
        return self._fused_sharded(tile)(fused_params, x)

    _PREQ_LIVE = object()  # sentinel: "read the live grid", distinct from
    # an explicit None snapshot (a non-preq model's locked snapshot) — the
    # live fallback on None would pair a concurrently-swapped preq grid
    # with the snapshot's old kernel weights

    def _fused_dispatch(self, fused_params: Any, chunk: np.ndarray,
                        preq_norm: Any = _PREQ_LIVE) -> Any:
        """Host chunk -> device probabilities through the active fused
        path. The int8 WIRE mode (q8 kernel, single device): the host runs
        the model's OWN first requantization (prequantize_rows_numpy) and
        ships 34 B/row instead of 120 — bit-identical math, the H2D
        transfer is what changes. Everything else ships rows in the
        kernel's wire dtype (bf16 for the bf16 kernel, f32 for q8).
        ``preq_norm`` must be snapshotted together with ``fused_params``
        when a concurrent swap is possible (pass the snapshot even when
        it is None — only the default reads the live grid)."""
        if preq_norm is Scorer._PREQ_LIVE:
            preq_norm = self._preq_norm
        if self._preq_wire and preq_norm is not None and self.mesh is None:
            q, s = self._fused_mod.prequantize_rows_numpy(preq_norm, chunk)
            tile = self._fused_mod.fit_tile(q.shape[0])
            if self.telemetry is None:
                qd, sd = jnp.asarray(q), jnp.asarray(s)
            else:
                from ccfd_tpu.observability.device import timed_put

                # the int8 wire's whole point is fewer H2D bytes — count
                # the bytes actually shipped, not the f32 equivalent
                qd = timed_put(self.telemetry, q.nbytes,
                               lambda: jnp.asarray(q))
                sd = timed_put(self.telemetry, s.nbytes,
                               lambda: jnp.asarray(s))
            return self._fused_mod.fused_mlp_q8_score_preq(
                fused_params, qd, sd, tile=tile,
                interpret=self._fused_interpret,
            )
        return self._fused_apply(
            fused_params,
            self._put_batch(chunk.astype(self._fused_in_dtype, copy=False)),
        )

    def _fused_sharded(self, tile: int) -> Any:
        """SPMD composition of the single-chip Pallas kernel: ``shard_map``
        over the data axis runs the kernel on each chip's row shard with the
        full (replicated) weights — the TPU-native form of the reference's
        "more replicas" scaling (reference deploy/frauddetection_cr.yaml:76).
        Cached per tile so each bucket compiles once."""
        fn = self._fused_sharded_cache.get(tile)
        if fn is None:
            from jax.sharding import PartitionSpec as P

            from ccfd_tpu.ops.shard_compat import shard_map
            from ccfd_tpu.parallel.mesh import DATA_AXIS

            def per_chip(p, xs):
                return self._fused_mod.fused_score(
                    p, xs, tile=tile, interpret=self._fused_interpret
                )

            fn = jax.jit(
                shard_map(
                    per_chip,
                    mesh=self.mesh,
                    in_specs=(P(), P(DATA_AXIS, None)),
                    out_specs=P(DATA_AXIS),
                    # pallas_call emits ShapeDtypeStructs without a vma
                    # annotation; the kernel is elementwise-per-shard, so
                    # the varying-across-mesh check adds nothing here
                    check_vma=False,
                )
            )
            self._fused_sharded_cache[tile] = fn
        return fn

    @property
    def params(self) -> Any:
        return self._params

    def bucket(self, n: int) -> int:
        for b in self.batch_sizes:
            if n <= b:
                return b
        return self.batch_sizes[-1]

    @property
    def fused(self) -> bool:
        return self._fused_params is not None

    def executable_grid(self) -> dict:
        """The compiled-executable set this scorer serves from — the row
        family's entry in the device telemetry plane's inventory (the seq
        family reports its (L, B) grid the same way)."""
        with self._lock:  # a first-dispatch of a new bucket inserts a
            # key; an unlocked scrape-iteration would race the resize
            counts = dict(self._dispatch_counts)
        out = {
            "model": self.spec.name,
            "batch_sizes": list(self.batch_sizes),
            "fused": self.fused,
            "int8_wire": bool(self._preq_wire
                              and self._preq_norm is not None),
            "host_tier_rows": self.host_tier_rows,
            "dispatches": {str(b): int(n)
                           for b, n in sorted(counts.items())},
        }
        if self.mesh is not None:
            out["mesh_devices"] = int(self.mesh.size)
            out["mesh_axes"] = {str(a): int(s)
                                for a, s in self.mesh.shape.items()}
        return out

    def warmup(self) -> None:
        """Compile every bucket (and measure the host-tier crossover).

        Deadline-aware when the dispatch guard is on: a wedged attachment at
        startup (the failure ADVICE r2 flagged for serve/router bring-up)
        marks the device wedged after ``CCFD_WARMUP_DEADLINE_S`` (default
        180 s — first XLA compile through a tunnel runs tens of seconds) and
        serving starts in host-fallback mode instead of hanging."""
        from ccfd_tpu.observability.profile import compile_stage

        def body() -> None:
            # compile attribution: warmup compiles the whole bucket grid;
            # the label rides the contextvar on whichever thread runs it
            with compile_stage("scorer.warmup"):
                self._warmup_body()

        if self._dispatcher is None:
            body()
            return
        import os as _os

        from ccfd_tpu.serving.dispatch import ScorerTimeout

        budget_s = float(_os.environ.get("CCFD_WARMUP_DEADLINE_S", "180"))
        try:
            self._dispatcher.call(body, budget_s)
        except ScorerTimeout:
            self.dispatch_timeouts += 1
            self._wedge.mark_wedged()

    @staticmethod
    def _is_lowering_error(e: Exception) -> bool:
        """Compile/lowering failures are permanent for this (kernel,
        backend) pair; runtime dispatch errors (attachment hiccups) are
        not. Classified by message because jax surfaces both through
        XlaRuntimeError."""
        text = f"{type(e).__name__}: {e}"
        if any(m in text for m in (
            "Mosaic", "lowering", "Unsupported", "NotImplemented",
            "UNIMPLEMENTED", "INVALID_ARGUMENT",
        )):
            return True
        # exceeding VMEM is permanent for this (kernel, shape) pair; the
        # message spells it "vmem" or "VMEM" depending on the layer. Bare
        # RESOURCE_EXHAUSTED without a vmem mention is NOT matched: that
        # is also XLA's transient-HBM-pressure status, and latching on it
        # would turn one recoverable OOM into a permanent downgrade.
        return "vmem" in text.lower()

    def _disable_fused(self, e: Exception, where: str) -> None:
        """Drop to the XLA graph. A lowering-class failure LATCHES fused
        off for the Scorer's lifetime — swap_params re-folds on every
        retrain publish, and folding is pure layout, so without the latch
        the broken kernel would come right back. A transient runtime
        error only disables until the next swap."""
        import logging

        latch = self._is_lowering_error(e)
        logging.getLogger(__name__).warning(
            "fused kernel failed at %s (%r); falling back to the XLA "
            "path%s", where, e, " permanently" if latch else " until the "
            "next params swap"
        )
        with self._lock:
            self._fused_params = None
            if latch:
                self._fused_disabled = True

    def _warmup_body(self) -> None:
        while True:
            try:
                for b in self.batch_sizes:
                    if self._fused_params is not None:
                        # through _fused_dispatch so the SERVING wire path
                        # (incl. the q8 int8 wire) is what compiles here
                        jax.block_until_ready(
                            self._fused_dispatch(
                                self._fused_params,
                                np.zeros((b, self.num_features),
                                         np.float32),
                            )
                        )
                    else:
                        jax.block_until_ready(
                            self._apply(
                                self._params,
                                self._put_batch(
                                    np.zeros((b, self.num_features),
                                             np.float32)
                                ),
                            )
                        )
                break
            except Exception as e:  # noqa: BLE001 - see below
                if self._fused_params is None:
                    raise
                # A Mosaic lowering failure surfaces at FIRST call, on the
                # only backend that can't be exercised in CI (real TPU).
                # Serving must degrade to the XLA graph — which computes
                # the same probabilities — not die at boot. Restart the
                # loop so every bucket gets its XLA executable (buckets
                # warmed fused-only before the failure would otherwise
                # compile lazily on the first live request).
                self._disable_fused(e, where="warmup")
        # autotune refines an ARMED auto tier (provisional 256 until
        # measured); host_tier_rows == 0 means the auto policy resolved the
        # tier OFF (cpu backend / mesh) — host params may still exist for
        # the wedge fallback and the C++ front, and must not re-arm it here
        if (
            self._host_tier_auto
            and self.host_tier_rows > 0
            and self._host_params is not None
        ):
            self.host_tier_rows = self._autotune_host_tier()

    def _autotune_host_tier(self) -> int:
        """Measure the crossover between host and device scoring.

        The right host-tier threshold is a property of the ATTACHMENT, not
        a constant: through a tunneled TPU one dispatch costs tens of ms
        and the host wins up to thousands of rows; on a locally-attached
        chip the RTT is sub-ms and the host should only keep tiny
        requests. Times the smallest compiled bucket's full dispatch
        (median of 5) against the host forward's per-row rate and returns
        the row count where host cost reaches half the device RTT —
        halving keeps latency strictly better on the host side while the
        device keeps every batch where its bandwidth starts to matter.
        Clamped to 8192 (the native front's per-request row cap).
        """
        import time as _time

        b = self.batch_sizes[0]
        with self._lock:
            params = self._params
            fused = self._fused_params
            host_params = self._host_params
            # same locked snapshot as the weights: _fused_dispatch's
            # contract — a concurrent swap_params must not pair the new
            # quantization grid with the old kernel weights mid-autotune
            preq = self._preq_norm
        if fused is not None:
            xb = np.zeros((b, self.num_features), np.float32)
            dispatch = lambda: self._fused_dispatch(fused, xb, preq)  # noqa: E731
        else:
            xf = np.zeros((b, self.num_features), np.float32)
            dispatch = lambda: self._apply(params, self._put_batch(xf))  # noqa: E731
        rtts = []
        for _ in range(5):
            t0 = _time.perf_counter()
            jax.block_until_ready(dispatch())
            rtts.append(_time.perf_counter() - t0)
        rtt_s = sorted(rtts)[len(rtts) // 2]

        probe_rows = 256
        xh = np.zeros((probe_rows, self.num_features), np.float32)
        self.spec.apply_numpy(host_params, xh)  # warm the numpy path
        n = 0
        t0 = _time.perf_counter()
        while True:
            self.spec.apply_numpy(host_params, xh)
            n += 1
            elapsed = _time.perf_counter() - t0
            if elapsed > 0.02 and n >= 3:
                break
        host_s_per_row = elapsed / (n * probe_rows)
        thr = int(rtt_s * 0.5 / max(host_s_per_row, 1e-9))
        return max(0, min(thr, 8192))

    def set_swap_gate(self, gate: Any) -> None:
        """Arm the partitioner's publish gate: every ``swap_params`` then
        pauses the router pool at a batch boundary first, so no worker's
        in-flight SPMD dispatch interleaves with the sharded re-layout
        (parallel/partition.py PublishGate; None disarms)."""
        self._swap_gate = gate

    def swap_params(self, new_params: Any) -> None:
        """Atomically publish retrained params without pausing serving.

        All staging (host gather, sharded H2D re-layout, fused fold, host
        casts) happens BEFORE the publish gate: double buffering keeps an
        in-flight dispatch safe against new buffers landing, so only the
        reference flip needs the router pool quiescent — a gated swap
        pauses the pool for a pointer swap, not a tree transfer."""
        staged = self._stage_swap(new_params)
        # prepublish: let dependent planes (fused decision grid) precompile
        # against the staged buffers BEFORE the gate/flip, so the first
        # serving dispatch after publish finds every bucket warm. Still on
        # the staging side — a slow or failing hook delays the publish, but
        # never pauses the pool and never blocks the flip itself.
        for hook in list(self._prepublish_hooks):
            try:
                hook(*staged)
            except Exception:  # noqa: BLE001 - must not break swaps
                logging.getLogger("ccfd_tpu.scorer").warning(
                    "prepublish hook %r raised; first serving dispatch "
                    "after this swap may pay its compile", hook,
                    exc_info=True)
        gate = self._swap_gate
        if gate is None:
            listeners, gen = self._commit_swap(*staged)
        else:
            with gate:
                listeners, gen = self._commit_swap(*staged)
        # listener delivery runs OUTSIDE the gate and the params lock
        # (listeners may be slow; the pool must not stay paused for them)
        self._notify_swap(new_params, staged[3], listeners, gen)

    def _stage_swap(self, new_params: Any) -> tuple:
        """Gate-free staging: every buffer the flip will install, built
        and device-committed up front.

        Copies into fresh buffers: ``device_put`` on already-committed arrays
        is an aliasing no-op, and aliased buffers would be deleted under us
        when the trainer's next donated step consumes its argument.
        """
        if self._param_sharding is not None:
            # re-lay the fresh tree onto the mesh with the serving sharding
            staged = jax.device_put(
                jax.tree.map(lambda a: np.array(a), new_params),
                self._param_sharding,
            )
        else:
            staged = jax.tree.map(lambda a: jnp.array(a, copy=True), new_params)
        jax.block_until_ready(staged)
        staged_fused = None
        staged_preq_norm = None
        # gate on the fused MODULE, not the current fused params: one
        # unfoldable swap drops to the XLA path, but a later foldable tree
        # must re-enable the kernel. A warmup LOWERING failure, however,
        # latches fused off for the Scorer's lifetime (_fused_disabled) —
        # folding is pure layout and would "succeed" right back into the
        # broken kernel.
        if (getattr(self, "_fused_mod", None) is not None
                and not getattr(self, "_fused_disabled", False)):
            try:
                folded = self._fused_mod.fold_for_kernel(staged)
                staged_fused = self._put_fused(folded)
                staged_preq_norm = self._preq_norm_of(folded)
                jax.block_until_ready(staged_fused)
            except (KeyError, TypeError, ValueError):
                staged_fused = None  # incompatible layout: drop to XLA path
                staged_preq_norm = None
        staged_host = None
        if self._host_params is not None:
            staged_host = jax.tree.map(_host_cast, new_params)
        return staged, staged_fused, staged_preq_norm, staged_host

    def _commit_swap(self, staged: Any, staged_fused: Any,
                     staged_preq_norm: Any, staged_host: Any
                     ) -> tuple[list, int]:
        """The flip: swap the serving references under the lock (the only
        part a publish gate quiesces the pool for)."""
        with self._lock:
            self._params = staged
            # never keep serving stale fused weights: an unfoldable tree
            # disables the fused path rather than pinning the old params
            self._fused_params = staged_fused
            if staged_fused is not None:
                # the int8 wire quantizes against the CURRENT normalizer;
                # a stale one would ship rows quantized on the old grid
                self._preq_norm = staged_preq_norm
            if staged_host is not None:
                self._host_params = staged_host
            listeners = list(self._swap_listeners)
            self._swap_gen += 1
            return listeners, self._swap_gen

    def _notify_swap(self, new_params: Any, staged_host: Any,
                     listeners: list, gen: int) -> None:
        if not listeners:
            return
        host_tree = (
            staged_host
            if staged_host is not None
            else jax.tree.map(_host_cast, new_params)
        )
        # outside the params lock (listeners may be slow), but serialized
        # and generation-checked: if a newer swap already delivered, this
        # older tree must not overwrite the listeners' copies
        with self._notify_lock:
            if gen <= self._swap_delivered_gen:
                return
            self._swap_delivered_gen = gen
            for fn in listeners:
                try:
                    fn(host_tree)
                except Exception:  # noqa: BLE001 - must not break swaps
                    # a listener that can't take the new tree (the shadow
                    # tap, the native host model) is now serving STALE
                    # params — that must be visible, not silent
                    logging.getLogger("ccfd_tpu.scorer").warning(
                        "swap listener %r raised; it may be serving stale "
                        "params", fn, exc_info=True)

    def add_prepublish_hook(self, fn: Any) -> None:
        """``fn(staged, staged_fused, staged_preq_norm, staged_host)`` runs
        inside every ``swap_params`` AFTER staging and BEFORE the publish
        gate/flip — the seam where the fused decision plane precompiles its
        (L, B) executable grid against the incoming params so the swap
        publishes warm. Hook errors are logged, never propagated."""
        with self._lock:
            self._prepublish_hooks.append(fn)

    def add_swap_listener(self, fn: Any) -> None:
        """``fn(host_params_numpy_tree)`` runs after every ``swap_params``."""
        with self._lock:
            self._swap_listeners.append(fn)

    def remove_swap_listener(self, fn: Any) -> None:
        with self._lock:
            if fn in self._swap_listeners:
                self._swap_listeners.remove(fn)

    # -- challenger slot (model lifecycle: shadow/canary scoring) ----------
    def install_challenger(self, version: int, params: Any) -> None:
        """Stage a challenger's host-params copy beside the champion.

        Double-buffered like ``swap_params``: the host cast happens into
        fresh buffers before the reference swaps under the lock, so an
        in-flight ``challenger_score`` keeps the old tree alive and the
        next call sees the new one. Requires a numpy host forward — the
        whole point of the slot is scoring off the device's critical path.
        """
        if self.spec.apply_numpy is None:
            raise RuntimeError(
                f"model {self.spec.name!r} has no host forward; the "
                f"challenger slot scores on the host by design")
        staged = jax.tree.map(_host_cast, params)
        with self._lock:
            self._challenger = (int(version), staged)

    def clear_challenger(self, version: int | None = None) -> None:
        """Remove the challenger; with ``version`` given, only that one
        (a stale clear must not evict a newer candidate)."""
        with self._lock:
            if (self._challenger is not None
                    and (version is None
                         or self._challenger[0] == int(version))):
                self._challenger = None

    @property
    def challenger_version(self) -> int | None:
        ch = self._challenger
        return ch[0] if ch is not None else None

    def challenger_score(self, x: np.ndarray) -> np.ndarray:
        """(n, F) -> (n,) proba_1 on the challenger slot's host params —
        no device round trip, never touches the champion path."""
        ch = self._challenger
        if ch is None:
            raise RuntimeError("no challenger installed")
        return np.asarray(
            self.spec.apply_numpy(ch[1], np.asarray(x, np.float32)),
            np.float32,
        )

    def score_pipelined(self, x: np.ndarray, depth: int = 2) -> np.ndarray:
        """Bulk scoring with ``depth`` dispatches in flight.

        JAX dispatch is async: by enqueuing the next chunk's H2D + kernel
        before blocking on the previous chunk's D2H, transfer and compute
        overlap. Wins when the host<->device wire dominates (large offline
        scoring runs); the synchronous ``score`` stays the latency path.
        """
        x = np.asarray(x, dtype=np.float32)
        n = x.shape[0]
        if n == 0:
            return np.zeros((0,), np.float32)
        with self._lock:
            params = self._params
            fused_params = self._fused_params
            preq_norm = self._preq_norm  # same snapshot as the weights: a
            # concurrent swap must not pair a new quantization grid with
            # the old kernel weights
        largest = self.batch_sizes[-1]
        pending: list[tuple[jax.Array, int]] = []
        chunks: list[np.ndarray] = []
        start = 0
        while start < n:
            take = min(n - start, largest)
            b = self.bucket(take)
            chunk = x[start : start + take]
            if take < b:
                chunk = np.concatenate(
                    [chunk, np.zeros((b - take, x.shape[1]), np.float32)]
                )
            # device-fault dispatch seam (runtime/faults.py): device_hang
            # stalls this dispatch past its watchdog, compile_stall bills
            # a synthetic re-trace — the taxonomy the heal ladder drills
            device_seam("dispatch")
            with self._lock:  # router workers share this scorer: the
                # read-modify-write must not lose increments
                self._dispatch_counts[b] = self._dispatch_counts.get(b, 0) + 1
            if fused_params is not None:
                try:
                    out = self._fused_dispatch(fused_params, chunk,
                                               preq_norm)
                # ccfd-lint: disable=counted-drops -- _disable_fused logs the failure with its latch decision; the request then scores on the XLA path
                except Exception as e:  # noqa: BLE001 - first dispatch of a
                    # swap-re-enabled kernel compiles HERE, not at warmup;
                    # a lowering failure must degrade this request to the
                    # XLA graph, not crash it
                    self._disable_fused(e, where="dispatch")
                    fused_params = None
                    out = self._apply(params, self._put_batch(chunk))
            else:
                out = self._apply(params, self._put_batch(chunk))
            pending.append((out, take))
            if len(pending) >= depth:
                done, took = pending.pop(0)
                self.host_syncs += 1
                chunks.append(np.asarray(done)[:took])
            start += take
        for done, took in pending:
            self.host_syncs += 1
            chunks.append(np.asarray(done)[:took])
        return np.concatenate(chunks).astype(np.float32)

    @property
    def has_host_forward(self) -> bool:
        """True when a numpy host forward (and a host params copy) exists —
        what the router's degraded host tier needs."""
        return self._host_params is not None and self.spec.apply_numpy is not None

    def host_score(self, x: np.ndarray) -> np.ndarray:
        """(n, F) -> (n,) proba_1 on the HOST params copy, no device
        round trip. This is the router degradation ladder's host tier
        (router/router.py): unlike ``score`` — whose own host fallback
        only engages on a wedge — this never touches the device edge, so
        it stays alive when that edge is partitioned or fault-injected."""
        with self._lock:
            host_params = self._host_params
        if host_params is None or self.spec.apply_numpy is None:
            raise RuntimeError(
                f"model {self.spec.name!r} has no host forward")
        return np.asarray(
            self.spec.apply_numpy(host_params, np.asarray(x, np.float32)),
            np.float32,
        )

    def score(self, x: np.ndarray) -> np.ndarray:
        """(n, F) float32 -> (n,) float32 proba_1, padding to a shape bucket.

        The synchronous latency path: small batches take the host tier
        (numpy forward, no device round trip — see ``host_tier_rows``);
        larger ones dispatch with one chunk in flight, same
        bucketing/padding as the pipelined bulk path.
        """
        x = np.asarray(x, dtype=np.float32)
        if 0 < x.shape[0] <= self.host_tier_rows:
            with self._lock:
                host_params = self._host_params
            return np.asarray(
                self.spec.apply_numpy(host_params, x), np.float32
            )
        if self._dispatcher is None:
            return self.score_pipelined(x, depth=1)
        return self._device_score_deadline(x)

    def _device_score_deadline(self, x: np.ndarray) -> np.ndarray:
        """Device path with a bounded round trip (serving latency path only;
        ``score_pipelined`` called directly — bulk/bench — is unbounded by
        design). Timeout => host fallback at ANY batch size, or
        :class:`~ccfd_tpu.serving.dispatch.ScorerTimeout` for the fronts to
        map to 503 when the model has no host forward."""
        from ccfd_tpu.serving.dispatch import ScorerTimeout

        if not self._wedge.wedged:
            # The deadline is calibrated for one bucketed dispatch; a
            # legitimately huge request scores as ceil(n/largest_bucket)
            # sequential chunks, and a healthy device must not be marked
            # wedged just because the request was big — scale the budget
            # by the chunk count (ADVICE r3).
            n_chunks = max(1, -(-len(x) // max(self.batch_sizes)))
            try:
                return self._dispatcher.call(
                    lambda: self.score_pipelined(x, depth=1),
                    self.dispatch_deadline_s * n_chunks,
                )
            except ScorerTimeout:
                self.dispatch_timeouts += 1
                self._wedge.mark_wedged()
        # wedged (now or already): no new device work queues behind the hang
        with self._lock:
            host_params = self._host_params
        if host_params is None or self.spec.apply_numpy is None:
            raise ScorerTimeout(
                f"device wedged for {self._wedge.wedged_for_s:.1f}s and "
                f"model {self.spec.name!r} has no host forward"
            )
        self.host_fallback_scores += 1
        return np.asarray(self.spec.apply_numpy(host_params, x), np.float32)
