"""REST serving layer with the Seldon wire contract, backed by the TPU scorer.

Replaces the reference's Seldon-Core engine + model pod
(reference deploy/model/modelfull.json:18-52, route
deploy/model/modelfull-route.yaml:1-12) with one process:

- ``POST /api/v0.1/predictions`` — the Seldon REST contract the router and
  KIE server call (reference deploy/router.yaml:65-68, README.md:454-459).
  Request: ``{"data": {"names": [...], "ndarray": [[...], ...]}}``;
  response mirrors the shape with ``names: ["proba_0", "proba_1"]`` and one
  probability row per input row.
- ``POST /predict`` — the jBPM prediction-service endpoint
  (reference ccd-service.yaml:61-62, README.md:379).
- Bearer-token auth when ``SELDON_TOKEN`` is configured
  (reference README.md:372-384, 447-451).
- ``GET /prometheus`` (and ``/metrics``) — scrape body carrying
  SeldonCore-dashboard-compatible series (reference
  deploy/grafana/SeldonCore.json:119-531):
  ``seldon_api_executor_client_requests_seconds_{count,sum,bucket}`` plus
  the ModelPrediction per-request gauges ``proba_1``/``Amount``/``V17``/
  ``V10`` (reference deploy/grafana/ModelPrediction.json:96-104).
- ``GET /health/status`` — Seldon-style readiness.

Implementation: a lean socket-level HTTP server (utils/fasthttp.py) —
no web framework is needed for a fixed four-route contract, and the
per-request parse cost is most of the REST latency budget once scoring
is fast. The canonical predict payload's matrix decodes NATIVELY (C++
strtof into float32, ccfd_tpu/native/decode.cpp) without touching
json.loads; the Python JSON path remains for names-remapped or unusual
payloads. The GIL is released during the XLA dispatch, so scoring
threads overlap host work.
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import Any

import numpy as np

from ccfd_tpu.config import Config
from ccfd_tpu.data.ccfd import FEATURE_NAMES
from ccfd_tpu.metrics.prom import Registry
from ccfd_tpu.native import decode_ndarray_json as native_decode_ndarray
from ccfd_tpu.serving.scorer import Scorer
from ccfd_tpu.utils.fasthttp import FastHTTPServer

_AMOUNT_COL = FEATURE_NAMES.index("Amount")
_V17_COL = FEATURE_NAMES.index("V17")
_V10_COL = FEATURE_NAMES.index("V10")


class PredictionServer:
    def __init__(
        self,
        scorer: Scorer,
        cfg: Config | None = None,
        registry: Registry | None = None,
        tracer=None,
        profiler=None,
    ):
        self.scorer = scorer
        self.cfg = cfg or Config()
        self.registry = registry or Registry()
        # stage profiler (observability/profile.py): handed to the
        # DynamicBatcher so the REST path's batcher-wait / device-dispatch
        # layers feed the SLO budget ledger
        self.profiler = profiler
        # observability/trace.py: predict requests join the caller's trace
        # (extracted traceparent -> "serving.predict" server span) and the
        # latency histogram carries the trace id as an exemplar. Python
        # transport only — the C++ native front never enters this handler.
        self.tracer = tracer
        r = self.registry
        # SeldonCore dashboard series (request rate / success / 4xx / 5xx and
        # latency quantiles come from this histogram + status-coded counter).
        self._h_latency = r.histogram(
            "seldon_api_executor_client_requests_seconds",
            "request latency by endpoint",
        )
        self._c_requests = r.counter(
            "seldon_api_executor_server_requests_total", "requests by code"
        )
        # Dispatch-health series (wedged-attachment visibility; the Serving
        # board alerts on these): wedged flag + timeout/fallback counters
        # folded from the scorer at scrape time.
        self._g_wedged = r.gauge(
            "ccfd_device_wedged", "1 while the device attachment is wedged"
        )
        self._c_dispatch_timeouts = r.counter(
            "ccfd_dispatch_timeouts_total", "device dispatches past deadline"
        )
        self._c_host_fallbacks = r.counter(
            "ccfd_host_fallback_scores_total",
            "requests scored on the host because the device was unavailable",
        )
        self._dispatch_timeouts_synced = 0
        self._host_fallbacks_synced = 0
        # ModelPrediction board: per-request feature/probability gauges.
        self._g_proba = r.gauge("proba_1", "last scored fraud probability")
        self._g_amount = r.gauge("Amount", "last scored transaction amount")
        self._g_v17 = r.gauge("V17", "last scored V17")
        self._g_v10 = r.gauge("V10", "last scored V10")
        self._httpd: FastHTTPServer | None = None
        self._gauges_set_ms = 0.0  # last Python-path gauge write (monotonic ms)
        # overload admission (runtime/overload.py): priority-tiered,
        # request-atomic reserve against an adaptive serving budget —
        # refused requests get an explicit 429 + retry-after instead of
        # queueing into a latency collapse. Requests carry their class in
        # an ``x-ccfd-priority`` header (bulk / normal / critical);
        # CCFD_OVERLOAD=0 removes the gate entirely.
        self.admission = None
        if self.cfg.overload_enabled:
            from ccfd_tpu.runtime.overload import AdmissionGate

            self.admission = AdmissionGate.from_config(
                self.cfg, r, max_rows=max(self.scorer.batch_sizes)
            )
        # dynamic batching (SURVEY.md §7 stage 2: request -> micro-batch
        # queue -> TPU): concurrent requests coalesce into one dispatch;
        # the adaptive policy adds no latency for a lone sequential client
        self.batcher = None
        if self.cfg.dynamic_batching:
            self._c_dispatches = r.counter(
                "serving_batcher_dispatches_total", "coalesced TPU dispatches"
            )
            self._c_batched_rows = r.counter(
                "serving_batcher_rows_total", "rows through the batcher"
            )
            self.batcher = self._make_batcher()

    def _make_batcher(self):
        from ccfd_tpu.serving.batcher import DynamicBatcher

        def on_dispatch(n_rows: int) -> None:
            self._c_dispatches.inc()
            self._c_batched_rows.inc(n_rows)

        codel = None
        max_queue_rows = 0
        on_shed = None
        if self.cfg.overload_enabled:
            # CoDel-style queue policy + priority-aware bounded queue
            # (runtime/overload.py); both default off via their Config
            # knobs, so plain deployments keep the historical semantics
            if self.cfg.overload_serve_codel_target_ms > 0:
                from ccfd_tpu.runtime.overload import DeadlinePolicy

                codel = DeadlinePolicy(
                    self.cfg.overload_serve_codel_target_ms / 1e3)
            max_queue_rows = self.cfg.overload_rest_queue_rows
            if codel is not None or max_queue_rows:
                from ccfd_tpu.runtime.overload import (
                    PRIORITY_NAMES,
                    _shed_counter,
                )

                c_shed = _shed_counter(self.registry)

                def on_shed(rows: int, priority: int) -> None:
                    c_shed.inc(rows, labels={
                        "priority": PRIORITY_NAMES.get(priority, "normal"),
                        "stage": "batcher"})

        return DynamicBatcher(
            self.scorer.score,
            max_batch=max(self.scorer.batch_sizes),
            deadline_ms=self.cfg.batch_deadline_ms,
            on_dispatch=on_dispatch,
            workers=self.cfg.batch_workers,
            codel=codel,
            max_queue_rows=max_queue_rows,
            on_shed=on_shed,
            profiler=self.profiler,
        )

    def _sync_dispatch_health(self) -> None:
        """Fold the scorer's dispatch-health counters into the registry
        (scrape-time pull keeps the hot path free of extra metric writes)."""
        s = self.scorer
        wedge = getattr(s, "_wedge", None)
        self._g_wedged.set(1.0 if (wedge is not None and wedge.wedged) else 0.0)
        d = int(getattr(s, "dispatch_timeouts", 0)) - self._dispatch_timeouts_synced
        if d > 0:
            self._c_dispatch_timeouts.inc(d)
            self._dispatch_timeouts_synced += d
        d = int(getattr(s, "host_fallback_scores", 0)) - self._host_fallbacks_synced
        if d > 0:
            self._c_host_fallbacks.inc(d)
            self._host_fallbacks_synced += d

    # -- scoring ----------------------------------------------------------
    def _score_matrix(self, x: np.ndarray, priority: int = 1) -> np.ndarray:
        if self.batcher is not None:
            proba = self.batcher.score(x, priority=priority)
        else:
            proba = self.scorer.score(x)
        if x.shape[0]:
            self._g_proba.set(float(proba[-1]))
            self._g_amount.set(float(x[-1, _AMOUNT_COL]))
            self._g_v17.set(float(x[-1, _V17_COL]))
            self._g_v10.set(float(x[-1, _V10_COL]))
            # recency stamp: the native front's scrape fold orders its
            # host-scored gauge values against this (ms, CLOCK_MONOTONIC)
            self._gauges_set_ms = time.monotonic() * 1e3
        return np.asarray(proba, np.float64)

    @staticmethod
    def _response_dict(proba: np.ndarray, model: str) -> dict:
        return {
            "data": {
                "names": ["proba_0", "proba_1"],
                # one vectorized build + tolist(): ~10x over per-element
                # float() pairs at typical request sizes
                "ndarray": np.stack([1.0 - proba, proba], axis=1).tolist(),
            },
            "meta": {"model": model},
        }

    def predict_ndarray(self, names: list[str], rows: list[list[float]],
                        priority: int = 1) -> dict:
        nf = self.scorer.num_features
        if names and names != list(FEATURE_NAMES):
            idx = {n: j for j, n in enumerate(FEATURE_NAMES)}
            x = np.zeros((len(rows), nf), np.float32)
            for i, row in enumerate(rows):
                for name, v in zip(names, row):
                    j = idx.get(name)
                    if j is not None:
                        x[i, j] = float(v)
        else:
            # hot path: uniform canonical-order rows convert in ONE numpy
            # call; the ragged/odd-width fallback keeps the lenient contract
            try:
                x = np.asarray(rows, np.float32)
            except ValueError:
                x = None
            if x is not None and x.ndim == 2 and x.shape[1] == nf:
                pass
            else:
                x = np.zeros((len(rows), nf), np.float32)
                for i, row in enumerate(rows):
                    x[i, : len(row)] = np.asarray(row, np.float32)[:nf]
        proba = self._score_matrix(x, priority=priority)
        return self._response_dict(proba, self.scorer.spec.name)

    # -- HTTP plumbing (FastHTTPServer handler contract) -------------------
    def _json(self, code: int, obj: Any) -> tuple[int, str, bytes]:
        self._c_requests.inc(labels={"code": str(code)})
        return code, "application/json", json.dumps(obj).encode()

    def _reject_overload(self, retry_after_s: float):
        """Explicit admission refusal: 429 with the retry-after hint both
        as an HTTP header (4-tuple; FastHTTPServer and the native front's
        misc path send it) and in the JSON body for clients that only
        read bodies."""
        self._c_requests.inc(labels={"code": "429"})
        body = json.dumps({
            "error": "overloaded",
            "retry_after_s": round(float(retry_after_s), 3),
        }).encode()
        retry = str(max(1, int(-(-retry_after_s // 1))))  # ceil, >= 1s
        return 429, "application/json", body, {"Retry-After": retry}

    def _authorized(self, headers: dict) -> bool:
        token = self.cfg.seldon_token
        if not token:
            return True
        auth = headers.get(b"authorization", b"").decode("latin-1")
        return auth == f"Bearer {token}"

    def _http_handler(
        self, method: str, path: str, headers: dict, body: bytes
    ) -> tuple[int, str, bytes]:
        if method == "GET":
            if path in ("/prometheus", "/metrics"):
                self._c_requests.inc(labels={"code": "200"})
                self._sync_dispatch_health()
                return 200, "text/plain", self.registry.render().encode()
            if path in ("/health/status", "/health", "/healthz"):
                return self._json(
                    200, {"status": "ok", "model": self.scorer.spec.name}
                )
            return self._json(404, {"error": "not found"})
        if method != "POST":
            return self._json(405, {"error": "method not allowed"})

        t0 = time.perf_counter()
        if not self._authorized(headers):
            return self._json(401, {"error": "unauthorized"})
        path = path.rstrip("/")
        if not (path.endswith("/predictions") or path == "/predict"):
            return self._json(404, {"error": "not found"})

        span_cm = contextlib.nullcontext()
        if self.tracer is not None:
            from ccfd_tpu.observability import trace as _trace

            span_cm = self.tracer.span(
                "serving.predict", parent=_trace.extract_context(headers),
                attrs={"endpoint": path})
        with span_cm as sp:
            trace_id = sp.trace_id if sp is not None else None
            # hot path: the canonical payload's matrix parses natively
            # (C++ strtof straight into float32, no json.loads); anything
            # unusual — a names header, ragged rows, no toolchain — falls
            # back to the Python JSON route below
            from ccfd_tpu.serving.dispatch import ScorerTimeout

            gate = self.admission
            pri = 1
            if gate is not None:
                from ccfd_tpu.runtime.overload import parse_priority

                pri = parse_priority(headers.get(b"x-ccfd-priority"))

            x = native_decode_ndarray(body, self.scorer.num_features)
            if x is not None:
                n_rows = x.shape[0]
                if gate is not None and not gate.try_admit(n_rows, pri):
                    return self._reject_overload(gate.retry_after_s)
                t_sc = time.perf_counter()
                try:
                    proba = self._score_matrix(x, priority=pri)
                except ScorerTimeout as e:
                    # wedged attachment, no host fallback for this model:
                    # bounded failure (503) instead of a hung connection — the
                    # server-side twin of the reference's SELDON_TIMEOUT.
                    # Returned, not raised, so the span must be marked here
                    # for the sampler's always-keep-errored rule.
                    if sp is not None:
                        sp.status = "error"
                    return self._json(503, {"error": f"scoring unavailable: {e}"})
                except Exception as e:
                    from ccfd_tpu.runtime.overload import OverloadShed

                    if isinstance(e, OverloadShed):  # queue policy shed
                        return self._reject_overload(e.retry_after_s)
                    raise
                finally:
                    if gate is not None:
                        gate.release(n_rows)
                if gate is not None:
                    gate.observe(time.perf_counter() - t_sc)
                out = self._response_dict(proba, self.scorer.spec.name)
            else:
                try:
                    payload = json.loads(body or b"{}")
                except (ValueError, json.JSONDecodeError):
                    return self._json(400, {"error": "malformed JSON body"})
                data = payload.get("data", {})
                rows = data.get("ndarray")
                if rows is None or not isinstance(rows, list):
                    return self._json(400, {"error": "missing data.ndarray in request"})
                if gate is not None and not gate.try_admit(len(rows), pri):
                    return self._reject_overload(gate.retry_after_s)
                t_sc = time.perf_counter()
                try:
                    out = self.predict_ndarray(data.get("names") or [], rows,
                                               priority=pri)
                except (TypeError, ValueError) as e:
                    return self._json(400, {"error": f"bad ndarray: {e}"})
                except ScorerTimeout as e:
                    if sp is not None:
                        sp.status = "error"
                    return self._json(503, {"error": f"scoring unavailable: {e}"})
                except Exception as e:
                    from ccfd_tpu.runtime.overload import OverloadShed

                    if isinstance(e, OverloadShed):
                        return self._reject_overload(e.retry_after_s)
                    raise
                finally:
                    if gate is not None:
                        gate.release(len(rows))
                if gate is not None:
                    gate.observe(time.perf_counter() - t_sc)
            self._h_latency.observe(
                time.perf_counter() - t0, labels={"endpoint": path},
                exemplar=({"trace_id": trace_id} if trace_id else None),
            )
            return self._json(200, out)

    def start(self, host: str | None = None, port: int | None = None) -> int:
        """Start serving on a background thread; returns the bound port.

        Transport selection: the C++ front (native/httpfront.cpp — epoll
        parsing + native payload decode + native response format; Python
        only scores batches) when the toolchain allows and
        ``cfg.native_front`` is on; the lean Python server otherwise.
        Same contract either way.
        """
        if self.cfg.dynamic_batching and self.batcher is None:
            # stop() tears the batcher down; a restarted server needs a
            # fresh one or every predict would fail on the stopped worker
            self.batcher = self._make_batcher()
        host = host if host is not None else self.cfg.serve_host
        port = port if port is not None else self.cfg.serve_port
        if self.cfg.native_front:
            try:
                from ccfd_tpu.serving.native_front import NativeFront

                front = NativeFront(self)
                bound = front.start(port, host=host)
                self._httpd = front
                return bound
            except (RuntimeError, OSError):
                pass  # no toolchain / bind conflict: Python transport below
        self._httpd = FastHTTPServer(
            (host, port), self._http_handler, name="ccfd-serving"
        ).start()
        return self._httpd.server_address[1]

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.stop()
            self._httpd = None
        if self.batcher is not None:
            self.batcher.stop()
            self.batcher = None  # start() recreates; direct predict_ndarray
            # on a stopped server falls back to unbatched scoring
