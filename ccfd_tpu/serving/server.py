"""REST serving layer with the Seldon wire contract, backed by the TPU scorer.

Replaces the reference's Seldon-Core engine + model pod
(reference deploy/model/modelfull.json:18-52, route
deploy/model/modelfull-route.yaml:1-12) with one process:

- ``POST /api/v0.1/predictions`` — the Seldon REST contract the router and
  KIE server call (reference deploy/router.yaml:65-68, README.md:454-459).
  Request: ``{"data": {"names": [...], "ndarray": [[...], ...]}}``;
  response mirrors the shape with ``names: ["proba_0", "proba_1"]`` and one
  probability row per input row.
- ``POST /predict`` — the jBPM prediction-service endpoint
  (reference ccd-service.yaml:61-62, README.md:379).
- Bearer-token auth when ``SELDON_TOKEN`` is configured
  (reference README.md:372-384, 447-451).
- ``GET /prometheus`` (and ``/metrics``) — scrape body carrying
  SeldonCore-dashboard-compatible series (reference
  deploy/grafana/SeldonCore.json:119-531):
  ``seldon_api_executor_client_requests_seconds_{count,sum,bucket}`` plus
  the ModelPrediction per-request gauges ``proba_1``/``Amount``/``V17``/
  ``V10`` (reference deploy/grafana/ModelPrediction.json:96-104).
- ``GET /health/status`` — Seldon-style readiness.

Implementation is a threaded stdlib HTTP server: no web framework is
needed for a fixed four-route contract, and keeping the handler thin
matters more for p99 than any framework feature. The GIL is released
during the XLA dispatch, so scoring threads overlap host work.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler
from typing import Any

from ccfd_tpu.utils.httpserver import FrameworkHTTPServer

import numpy as np

from ccfd_tpu.config import Config
from ccfd_tpu.data.ccfd import FEATURE_NAMES
from ccfd_tpu.metrics.prom import Registry
from ccfd_tpu.serving.scorer import Scorer


class PredictionServer:
    def __init__(
        self,
        scorer: Scorer,
        cfg: Config | None = None,
        registry: Registry | None = None,
    ):
        self.scorer = scorer
        self.cfg = cfg or Config()
        self.registry = registry or Registry()
        r = self.registry
        # SeldonCore dashboard series (request rate / success / 4xx / 5xx and
        # latency quantiles come from this histogram + status-coded counter).
        self._h_latency = r.histogram(
            "seldon_api_executor_client_requests_seconds",
            "request latency by endpoint",
        )
        self._c_requests = r.counter(
            "seldon_api_executor_server_requests_total", "requests by code"
        )
        # ModelPrediction board: per-request feature/probability gauges.
        self._g_proba = r.gauge("proba_1", "last scored fraud probability")
        self._g_amount = r.gauge("Amount", "last scored transaction amount")
        self._g_v17 = r.gauge("V17", "last scored V17")
        self._g_v10 = r.gauge("V10", "last scored V10")
        self._httpd: FrameworkHTTPServer | None = None
        # dynamic batching (SURVEY.md §7 stage 2: request -> micro-batch
        # queue -> TPU): concurrent requests coalesce into one dispatch;
        # the adaptive policy adds no latency for a lone sequential client
        self.batcher = None
        if self.cfg.dynamic_batching:
            self._c_dispatches = r.counter(
                "serving_batcher_dispatches_total", "coalesced TPU dispatches"
            )
            self._c_batched_rows = r.counter(
                "serving_batcher_rows_total", "rows through the batcher"
            )
            self.batcher = self._make_batcher()

    def _make_batcher(self):
        from ccfd_tpu.serving.batcher import DynamicBatcher

        def on_dispatch(n_rows: int) -> None:
            self._c_dispatches.inc()
            self._c_batched_rows.inc(n_rows)

        return DynamicBatcher(
            self.scorer.score,
            max_batch=max(self.scorer.batch_sizes),
            deadline_ms=self.cfg.batch_deadline_ms,
            on_dispatch=on_dispatch,
            workers=self.cfg.batch_workers,
        )

    # -- scoring ----------------------------------------------------------
    def predict_ndarray(self, names: list[str], rows: list[list[float]]) -> dict:
        nf = self.scorer.num_features
        if names and names != list(FEATURE_NAMES):
            idx = {n: j for j, n in enumerate(FEATURE_NAMES)}
            x = np.zeros((len(rows), nf), np.float32)
            for i, row in enumerate(rows):
                for name, v in zip(names, row):
                    j = idx.get(name)
                    if j is not None:
                        x[i, j] = float(v)
        else:
            # hot path: uniform canonical-order rows convert in ONE numpy
            # call; the ragged/odd-width fallback keeps the lenient contract
            try:
                x = np.asarray(rows, np.float32)
            except ValueError:
                x = None
            if x is not None and x.ndim == 2 and x.shape[1] == nf:
                pass
            else:
                x = np.zeros((len(rows), nf), np.float32)
                for i, row in enumerate(rows):
                    x[i, : len(row)] = np.asarray(row, np.float32)[:nf]
        if self.batcher is not None:
            proba = self.batcher.score(x)
        else:
            proba = self.scorer.score(x)
        if len(rows):
            self._g_proba.set(float(proba[-1]))
            self._g_amount.set(float(x[-1, FEATURE_NAMES.index("Amount")]))
            self._g_v17.set(float(x[-1, FEATURE_NAMES.index("V17")]))
            self._g_v10.set(float(x[-1, FEATURE_NAMES.index("V10")]))
        proba = np.asarray(proba, np.float64)
        return {
            "data": {
                "names": ["proba_0", "proba_1"],
                # one vectorized build + tolist(): ~10x over per-element
                # float() pairs at typical request sizes
                "ndarray": np.stack([1.0 - proba, proba], axis=1).tolist(),
            },
            "meta": {"model": self.scorer.spec.name},
        }

    # -- HTTP plumbing ----------------------------------------------------
    def _handler_class(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # quiet
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                server._c_requests.inc(labels={"code": str(code)})

            def _send_json(self, code: int, obj: Any) -> None:
                self._send(code, json.dumps(obj).encode(), "application/json")

            def _authorized(self) -> bool:
                token = server.cfg.seldon_token
                if not token:
                    return True
                auth = self.headers.get("Authorization", "")
                return auth == f"Bearer {token}"

            def do_GET(self):
                if self.path in ("/prometheus", "/metrics"):
                    self._send(200, server.registry.render().encode(), "text/plain")
                elif self.path in ("/health/status", "/health", "/healthz"):
                    self._send_json(200, {"status": "ok", "model": server.scorer.spec.name})
                else:
                    self._send_json(404, {"error": "not found"})

            def do_POST(self):
                t0 = time.perf_counter()
                # Always drain the body first: on HTTP/1.1 keep-alive an
                # unread body would be parsed as the next request line by the
                # reused connection (pooled clients hit this on 401/404).
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                except ValueError:
                    length = 0
                raw = self.rfile.read(length) if length else b"{}"
                if not self._authorized():
                    self._send_json(401, {"error": "unauthorized"})
                    return
                try:
                    payload = json.loads(raw or b"{}")
                except (ValueError, json.JSONDecodeError):
                    self._send_json(400, {"error": "malformed JSON body"})
                    return
                path = self.path.rstrip("/")
                if path.endswith("/predictions") or path == "/predict":
                    data = payload.get("data", {})
                    rows = data.get("ndarray")
                    if rows is None or not isinstance(rows, list):
                        self._send_json(
                            400, {"error": "missing data.ndarray in request"}
                        )
                        return
                    try:
                        out = server.predict_ndarray(data.get("names") or [], rows)
                    except (TypeError, ValueError) as e:
                        self._send_json(400, {"error": f"bad ndarray: {e}"})
                        return
                    server._h_latency.observe(
                        time.perf_counter() - t0, labels={"endpoint": path}
                    )
                    self._send_json(200, out)
                else:
                    self._send_json(404, {"error": "not found"})

        return Handler

    def start(self, host: str | None = None, port: int | None = None) -> int:
        """Start serving on a background thread; returns the bound port."""
        if self.cfg.dynamic_batching and self.batcher is None:
            # stop() tears the batcher down; a restarted server needs a
            # fresh one or every predict would fail on the stopped worker
            self.batcher = self._make_batcher()
        host = host if host is not None else self.cfg.serve_host
        port = port if port is not None else self.cfg.serve_port
        self._httpd = FrameworkHTTPServer((host, port), self._handler_class())
        t = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="ccfd-serving"
        )
        t.start()
        return self._httpd.server_address[1]

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self.batcher is not None:
            self.batcher.stop()
            self.batcher = None  # start() recreates; direct predict_ndarray
            # on a stopped server falls back to unbatched scoring
