"""Per-customer transaction history for the sequence scorer.

The seq model (models/seq.py) scores the NEWEST transaction given the
customer's recent history (B, L, F). Single-row REST scoring is stateless
by design (the Seldon contract); history lives where the stream lives —
in the routing tier, which already sees every transaction in arrival
order. This module is that state:

- ``HistoryStore`` — fixed-depth ring buffer per customer, bounded total
  customers (LRU eviction at the cap), thread-safe. Mutation is
  two-phase: ``prepare()`` stages copies, ``commit()`` publishes them —
  a failed scorer dispatch must not leave transactions in history that
  were never routed. The store is CHECKPOINTABLE (snapshot/restore), and
  the recovery coordinator treats it as pipeline state: after a crash
  rewind, replayed records re-build exactly the histories the cut had —
  without this, at-least-once redelivery would append every replayed
  transaction a second time and silently corrupt every active
  customer's context.
- ``SeqScorer`` — the router-facing scorer: takes this poll's rows + ids,
  assembles the (bucket, L, F) batch (cold customers zero-pad on the
  LEFT so the newest transaction is always the last token — the readout
  position), and runs one jitted dispatch per micro-batch over bucketed
  batch sizes, the same static-shape discipline as the row scorer
  (serving/scorer.py; the bucketing is intentionally the same shape —
  single-device serving here, so the row scorer's data-parallel bucket
  rounding does not apply).

TPU-first notes: histories assemble host-side into one contiguous array
per micro-batch (one transfer, one dispatch — never per-customer gathers
on device); L is static so XLA sees a fixed (bucket, L, F) shape; the
model runs bf16 with f32 accumulation.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any

import numpy as np

from ccfd_tpu.data.ccfd import NUM_FEATURES


class HistoryStore:
    """Fixed-depth per-customer ring buffers with bounded total keys.

    Memory bound: ``max_customers * length * num_features * 4`` bytes —
    the default (20k x 64 x 30 x f32) admits ~150 MB resident on the
    serving host; size the cap to the deployment's live-customer working
    set, not its total cardinality (LRU keeps the hot set)."""

    def __init__(self, length: int = 64, num_features: int = NUM_FEATURES,
                 max_customers: int = 20_000):
        if length < 1:
            raise ValueError("history length must be >= 1")
        self.length = int(length)
        self.num_features = int(num_features)
        self.max_customers = int(max_customers)
        self._lock = threading.Lock()
        # id -> (buffer (L, F) f32, filled count); OrderedDict as LRU:
        # move_to_end on touch, evict the coldest when over cap
        self._h: OrderedDict[Any, tuple[np.ndarray, int]] = OrderedDict()
        # epoch generation: restore() bumps it and commit() drops staged
        # chunks from an older generation — a scorer dispatch that was in
        # flight across a crash restore (the unacked-barrier path) must
        # not land its doomed-epoch rows on the restored state (the
        # engine's equivalent guard is Engine._check_alive)
        self._gen = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._h)

    def prepare(
        self, ids: list, rows: np.ndarray, overlay: dict | None = None
    ) -> tuple[np.ndarray, tuple[int, dict]]:
        """Stage this chunk: return the (B, L, F) batch of post-append
        histories (newest last) plus a staged token, WITHOUT mutating the
        store. ``commit()`` publishes staged state only after the scorer
        dispatch succeeded — a dropped batch (transient scorer failure)
        must leave histories exactly matching the routed stream.

        A customer appearing twice in one chunk sees its earlier
        same-chunk rows in the later assembly; ``overlay`` extends that
        visibility across the chunks of ONE router batch (the caller
        accumulates staged dicts and commits once). ``None`` ids are
        anonymous: scored against an empty history and NEVER stored — a
        bounded store must not spend its cap (and evict real customers)
        on keys no future record can match."""
        rows = np.ascontiguousarray(rows, np.float32)
        n = len(rows)
        out = np.zeros((n, self.length, self.num_features), np.float32)
        staged: dict[Any, tuple[np.ndarray, int]] = {}
        with self._lock:
            gen = self._gen
            for i in range(n):
                key = ids[i]
                if key is None:
                    # anonymous: cold context + this row as the readout
                    out[i, -1] = rows[i]
                    continue
                ent = staged.get(key)
                if ent is None and overlay is not None:
                    ent = overlay.get(key)
                    if ent is not None:  # earlier chunk's staged copy
                        ent = (ent[0].copy(), ent[1])
                if ent is None:
                    ent = self._h.get(key)
                    if ent is None:
                        buf = np.zeros((self.length, self.num_features),
                                       np.float32)
                        filled = 0
                    else:  # copy-on-write: the live buffer stays untouched
                        buf, filled = ent
                        buf = buf.copy()
                else:
                    buf, filled = ent
                # shift-left ring: newest transaction is always row L-1
                # (the seq model's readout token); cold-start zeros stay
                # on the left until the buffer fills
                buf[:-1] = buf[1:]
                buf[-1] = rows[i]
                filled = min(filled + 1, self.length)
                staged[key] = (buf, filled)
                out[i] = buf
        return out, (gen, staged)

    def commit(self, token: tuple[int, dict]) -> bool:
        """Publish a prepared chunk (call only after a successful
        dispatch). Evicts the coldest keys past the cap. Returns False —
        and changes nothing — when the store was restored since the
        prepare (stale generation: the rewound bus will re-drive those
        records onto the restored state)."""
        gen, staged = token
        if not staged:
            return True
        with self._lock:
            if gen != self._gen:
                return False
            for key, ent in staged.items():
                if key in self._h:
                    self._h.move_to_end(key)
                self._h[key] = ent
            while len(self._h) > self.max_customers:
                self._h.popitem(last=False)
        return True

    # -- checkpoint surface (pipeline state, like the engine) --------------
    def snapshot(self) -> dict:
        """Copy-only state for the recovery coordinator's cut: runs under
        the checkpoint barrier, so buffers are returned as numpy COPIES
        (fast memcpy) — the coordinator JSON-normalizes outside the
        barrier (recovery.py _np_jsonable); ``restore`` accepts either
        form."""
        with self._lock:
            return {
                "version": 1,
                "length": self.length,
                "num_features": self.num_features,
                "customers": [
                    [key, buf.copy(), filled]
                    for key, (buf, filled) in self._h.items()
                ],
            }

    def restore(self, snap: dict | None) -> None:
        """Replace the store's content with a snapshot's (crash recovery:
        the rewound bus re-drives post-cut records, re-building exactly
        the histories the cut had). ``None`` resets to empty (genesis
        restore — replay from offset 0 rebuilds everything)."""
        if snap is None:
            with self._lock:
                self._h.clear()
                self._gen += 1
            return
        if snap.get("version") != 1:
            raise ValueError(f"unknown history snapshot {snap.get('version')!r}")
        if (int(snap["length"]) != self.length
                or int(snap["num_features"]) != self.num_features):
            raise ValueError("history snapshot shape mismatch")
        with self._lock:
            self._h.clear()
            self._gen += 1  # in-flight prepares become stale commits
            for key, buf, filled in snap["customers"]:
                self._h[key] = (
                    np.asarray(buf, np.float32).reshape(
                        self.length, self.num_features
                    ),
                    int(filled),
                )

    def snapshot_counts(self) -> dict:
        with self._lock:
            return {"customers": len(self._h), "length": self.length}


class SeqScorer:
    """History-aware scorer with the row scorer's serving discipline:
    bucketed static shapes, one jit dispatch per micro-batch."""

    def __init__(
        self,
        params: Any,
        length: int = 64,
        batch_sizes: tuple = (16, 128, 1024, 4096),
        compute_dtype: str = "bfloat16",
        max_customers: int = 20_000,
        registry: Any = None,
        mesh: Any = None,
    ):
        """``mesh``: serve the seq dispatch over a device mesh — history
        batches split over the ``"data"`` axis, params replicated (the
        same SPMD layout the row Scorer's data-axis path uses; history
        ASSEMBLY stays host-side either way, which is exactly what the
        bench's seq_pipeline assembly-vs-dispatch split measures).
        Bucket sizes round up to data-axis multiples so every shard gets
        identical static shapes."""
        import jax
        import jax.numpy as jnp

        from ccfd_tpu.models import seq as seq_mod

        self.store = HistoryStore(length=length, max_customers=max_customers)
        dtype = jnp.bfloat16 if compute_dtype == "bfloat16" else jnp.float32
        self.mesh = mesh
        self._batch_sharding = None
        if mesh is None:
            self.params = params

            @jax.jit
            def _apply(p, xs):
                return seq_mod.apply(p, xs, dtype)

        else:
            from jax.sharding import NamedSharding, PartitionSpec

            from ccfd_tpu.parallel.sharding import replicated

            # split over EVERY axis the mesh actually has: the data axis
            # alone would idle the model-axis devices on a
            # replicated-param elementwise path, and naming an axis the
            # mesh lacks (e.g. a data-only mesh) would raise
            part_axes = tuple(a for a in ("data", "model")
                              if mesh.shape.get(a, 1) > 1) \
                or tuple(mesh.axis_names[:1])
            dsize = 1
            for a in part_axes:
                dsize *= mesh.shape[a]
            batch_sizes = tuple(
                max(1, -(-b // dsize)) * dsize for b in batch_sizes
            )
            self.params = jax.device_put(params, replicated(mesh))
            self._batch_sharding = NamedSharding(
                mesh, PartitionSpec(part_axes, None, None))
            _apply = jax.jit(
                lambda p, xs: seq_mod.apply(p, xs, dtype),
                out_shardings=NamedSharding(mesh, PartitionSpec(part_axes)),
            )
        self.batch_sizes = tuple(sorted(set(batch_sizes)))
        self._apply = _apply
        self._jax = jax
        self._params_lock = threading.Lock()
        self._g_customers = None
        if registry is not None:
            self._g_customers = registry.gauge(
                "seq_history_customers", "customers with live history"
            )

    def _put_hist(self, hist: np.ndarray):
        """H2D with placement: on a mesh each device gets its row shard."""
        if self._batch_sharding is None:
            return hist
        return self._jax.device_put(hist, self._batch_sharding)

    def swap_params(self, params: Any) -> None:
        """Hot-swap model weights (the online-retrain surface the row
        scorer exposes; same treedef ⇒ the jit cache is reused)."""
        if self.mesh is not None:
            from ccfd_tpu.parallel.sharding import replicated

            params = self._jax.device_put(params, replicated(self.mesh))
        with self._params_lock:
            self.params = params

    def warmup(self) -> None:
        for b in self.batch_sizes:
            xs = np.zeros((b, self.store.length, self.store.num_features),
                          np.float32)
            self._jax.block_until_ready(
                self._apply(self.params, self._put_hist(xs)))

    def _bucket(self, n: int) -> int:
        for b in self.batch_sizes:
            if n <= b:
                return b
        return self.batch_sizes[-1]

    def score(self, x: np.ndarray, ids: list | None = None) -> np.ndarray:
        """Router-compatible scorer: (B, F) rows -> (B,) probabilities,
        each conditioned on that customer's history. Rows with no id
        (``ids`` absent or None entries) score against an empty history
        and are not tracked."""
        n = len(x)
        if n == 0:
            return np.zeros((0,), np.float32)
        if ids is None:
            ids = [None] * n
        out = np.empty((n,), np.float32)
        start = 0
        largest = self.batch_sizes[-1]
        # ONE commit for the whole router batch, after EVERY chunk's
        # dispatch succeeded: a mid-batch failure drops the batch at the
        # router, and a half-committed history would diverge from the
        # routed stream. The overlay keeps same-customer visibility
        # across chunks; the generation token makes a commit that raced
        # a crash restore a no-op (the rewind re-drives those records).
        merged: dict = {}
        gen = None
        while start < n:
            stop = min(start + largest, n)
            hist, (gen, staged) = self.store.prepare(
                ids[start:stop], x[start:stop], overlay=merged
            )
            m = stop - start
            bucket = self._bucket(m)
            if m < bucket:
                hist = np.concatenate(
                    [hist, np.zeros((bucket - m, *hist.shape[1:]),
                                    np.float32)]
                )
            with self._params_lock:
                params = self.params
            proba = np.asarray(self._apply(params, self._put_hist(hist)))
            merged.update(staged)
            out[start:stop] = proba[:m]
            start = stop
        if gen is not None:
            self.store.commit((gen, merged))
        if self._g_customers is not None:
            self._g_customers.set(float(len(self.store)))
        return out

    # Router contract: passing the SeqScorer OBJECT as the router's
    # score_fn makes it callable for the plain (x,) path, and the router
    # detects score_with_ids and feeds decoded records alongside x
    def __call__(self, x: np.ndarray) -> np.ndarray:
        return self.score(x)

    def score_with_ids(self, txs: list, x: np.ndarray) -> np.ndarray:
        """Batch entry for the router: ids come from each record's
        ``customer_id``/``id`` field; records with neither are anonymous
        (scored cold, not tracked)."""
        ids: list = []
        for t in txs:
            key = None
            if isinstance(t, dict):
                key = t.get("customer_id")
                if key is None:
                    key = t.get("id")
            ids.append(key)
        return self.score(x, ids)
