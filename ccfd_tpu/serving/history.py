"""Per-customer transaction history for the sequence scorer.

The seq model (models/seq.py) scores the NEWEST transaction given the
customer's recent history (B, L, F). Single-row REST scoring is stateless
by design (the Seldon contract); history lives where the stream lives —
in the routing tier, which already sees every transaction in arrival
order. This module is that state, reworked (round 11) from a synchronous
chunk loop into an overlapped serving dataflow — BENCH_r05 measured the
old path at 1412 ms device dispatch vs 13 ms assembly per bucket
(assembly_fraction 0.009): entirely dispatch-bound, serialized anyway.

- ``HistoryStore`` — fixed-depth ring buffer per customer, bounded total
  customers (LRU eviction at the cap), INTERNALLY STRIPED by key hash:
  N stripes with per-stripe locks so ParallelRouter workers stop
  convoying on one global lock, a global monotonic touch-stamp keeping
  LRU eviction exact across stripes, an all-anonymous fast path that
  takes no lock at all (cold REST scoring), and a vectorized ``prepare``
  for the common no-duplicate-key chunk. Mutation is two-phase:
  ``prepare()`` stages copies, ``commit()`` publishes them — a failed
  scorer dispatch must not leave transactions in history that were never
  routed. The store is CHECKPOINTABLE (snapshot/restore); ``snapshot``
  is stripe-incremental (clean stripes reuse the previous snapshot's
  entry list — no 150 MB memcpy under the checkpoint barrier; buffers
  are immutable-by-convention, so entries are shared, never copied), and
  the recovery coordinator treats the store as pipeline state: after a
  crash rewind, replayed records re-build exactly the histories the cut
  had — without this, at-least-once redelivery would append every
  replayed transaction a second time and silently corrupt every active
  customer's context.
- ``SeqScorer`` — the router-facing scorer, now an overlapped dataflow:
  each (L-bucket, B-bucket) group's device call is ENQUEUED (JAX async
  dispatch) and the next group assembles while it runs; results resolve
  (``np.asarray``) only when the bounded in-flight window (``inflight``)
  fills or the batch ends, and the store commits once, after every
  dispatch resolved — a crash restore racing an in-flight dispatch
  drops the whole batch's commit (stale generation, counted in
  ``seq_stale_commits_total``), and when the PR 6 dispatch watchdog
  abandons a hung batch whose commit later lands CONCURRENTLY with the
  worker's next batch, the store's per-key optimistic check skips the
  contended keys instead of clobbering newer state
  (``HistoryStore.contended_skips``; the skipped appends are in the
  routed stream, so the next crash-restore replay recovers them). Rows bucket by
  HISTORY LENGTH as well as batch size: a mostly-cold row (filled << L)
  dispatches through a short-sequence executable (the ``len_buckets``
  ladder) instead of padding to full L, with per-(L, B)-bucket hit
  counters; shapes stay static per (L, B) pair so XLA never re-traces.
  The device graph is ``seq.apply_serving`` (exact last-block readout
  optimization) or ``ops/seq_quant.apply`` when the installed params are
  the int8 variant — ``swap_params`` re-binds the jit by sniffing the
  param tree, which is how a lifecycle-promoted ``seq_q8`` candidate
  takes over serving.

TPU-first notes: histories assemble host-side into one contiguous array
per micro-batch (one transfer, one dispatch — never per-customer gathers
on device); every L bucket is static so XLA sees fixed (bucket, L, F)
shapes; the model runs bf16 with f32 accumulation.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict, deque
from typing import Any

import numpy as np

from ccfd_tpu.data.ccfd import NUM_FEATURES
from ccfd_tpu.runtime.faults import device_seam

DEFAULT_STRIPES = 8
# short-sequence ladder OFF by default: bucketed windows attend fewer
# zero-pad tokens than the full-L graph (reference_attention has no
# padding mask), so scores for cold rows differ between rungs — arming
# the ladder is an explicit serving choice (seq.len_buckets /
# CCFD_SEQ_LEN_BUCKETS), the same opt-in posture as the CoDel deadline
DEFAULT_LEN_BUCKETS: tuple = ()
DEFAULT_INFLIGHT = 2


class _Stripe:
    __slots__ = ("lock", "h", "dirty", "cache")

    def __init__(self) -> None:
        self.lock = threading.Lock()
        # key -> (buffer (L, F) f32, filled count, touch stamp)
        self.h: OrderedDict[Any, tuple[np.ndarray, int, int]] = OrderedDict()
        self.dirty = True
        self.cache: list[tuple[int, Any, np.ndarray, int]] = []


class HistoryStore:
    """Fixed-depth per-customer ring buffers with bounded total keys.

    Memory bound: ``max_customers * length * num_features * 4`` bytes —
    the default (20k x 64 x 30 x f32) admits ~150 MB resident on the
    serving host; size the cap to the deployment's live-customer working
    set, not its total cardinality (LRU keeps the hot set).

    Concurrency: reads/stages take only the key's stripe lock (and the
    all-anonymous path none); ``commit``/``restore``/``snapshot``
    serialize on one commit lock (commits are per router batch — rare
    next to prepares — and a restore interleaving a half-published
    commit would corrupt the cut). Stored buffers are IMMUTABLE by
    convention: prepare copies before mutating and commit replaces
    entries, which is what lets lookups hand out references under the
    stripe lock and snapshots share entries across generations."""

    def __init__(self, length: int = 64, num_features: int = NUM_FEATURES,
                 max_customers: int = 20_000, stripes: int = DEFAULT_STRIPES):
        if length < 1:
            raise ValueError("history length must be >= 1")
        self.length = int(length)
        self.num_features = int(num_features)
        self.max_customers = int(max_customers)
        self.stripes = max(1, int(stripes))
        self._stripes = [_Stripe() for _ in range(self.stripes)]
        self._commit_lock = threading.Lock()
        self._count_lock = threading.Lock()
        self._total = 0
        # global touch stamp: commit order defines recency ACROSS stripes,
        # so LRU eviction at the cap stays exact despite per-stripe LRU
        # order (itertools.count().__next__ is GIL-atomic)
        self._stamp = itertools.count().__next__
        # commits skipped by the per-key optimistic check (see commit());
        # nonzero means concurrent same-key batches raced — e.g. a
        # watchdog-abandoned dispatch's late commit
        self._contended = 0
        # epoch generation: restore() bumps it and commit() drops staged
        # chunks from an older generation — a scorer dispatch that was in
        # flight across a crash restore (the unacked-barrier path) must
        # not land its doomed-epoch rows on the restored state (the
        # engine's equivalent guard is Engine._check_alive)
        self._gen = 0

    def _stripe_of(self, key: Any) -> _Stripe:
        return self._stripes[hash(key) % self.stripes]

    def __len__(self) -> int:
        with self._count_lock:
            return self._total

    # -- staging ------------------------------------------------------------
    # ccfd-lint: hot-path
    def prepare(
        self, ids: list, rows: np.ndarray, overlay: dict | None = None
    ) -> tuple[np.ndarray, tuple[int, dict, np.ndarray]]:
        """Stage this chunk: return the (B, L, F) batch of post-append
        histories (newest last) plus a token ``(gen, staged, filled)``,
        WITHOUT mutating the store. ``commit()`` publishes staged state
        only after the scorer dispatch succeeded — a dropped batch
        (transient scorer failure) must leave histories exactly matching
        the routed stream. ``filled`` is the per-row post-append history
        depth — what the scorer's L-bucket ladder partitions on.

        A customer appearing twice in one chunk sees its earlier
        same-chunk rows in the later assembly; ``overlay`` extends that
        visibility across the chunks of ONE router batch (the caller
        accumulates staged dicts and commits once). ``None`` ids are
        anonymous: scored against an empty history and NEVER stored — a
        bounded store must not spend its cap (and evict real customers)
        on keys no future record can match. An ALL-anonymous chunk takes
        no lock and stages nothing (the cold-REST fast path)."""
        rows = np.ascontiguousarray(rows, np.float32)
        n = len(rows)
        L = self.length
        out = np.zeros((n, L, self.num_features), np.float32)
        filled_out = np.ones((n,), np.int32)
        gen = self._gen
        if n:
            out[:, -1] = rows
        keyed = [(i, ids[i]) for i in range(n) if ids[i] is not None]
        if not keyed:
            return out, (gen, {}, filled_out)
        keys = [k for _, k in keyed]
        if len(set(keys)) == len(keys):
            staged = self._prepare_unique(keyed, rows, out, filled_out,
                                          overlay)
        else:
            staged = self._prepare_general(ids, rows, out, filled_out,
                                           overlay)
        return out, (gen, staged, filled_out)

    def _lookup_refs(self, pairs: list[tuple[int, Any]]) -> dict:
        """(row, key) pairs -> {row: (buf_ref, filled)} for keys live in
        the store; one pass per touched stripe, references only under the
        lock (buffers are immutable, see class docstring)."""
        by_stripe: dict[int, list[tuple[int, Any]]] = {}
        for i, key in pairs:
            by_stripe.setdefault(hash(key) % self.stripes, []).append((i, key))
        hits: dict[int, tuple[np.ndarray, int, int]] = {}
        for si, group in by_stripe.items():
            st = self._stripes[si]
            with st.lock:
                h = st.h
                for i, key in group:
                    ent = h.get(key)
                    if ent is not None:
                        hits[i] = ent  # (buf, filled, stamp) — immutable
        return hits

    def _prepare_unique(self, keyed, rows, out, filled_out, overlay) -> dict:
        """No key repeats in the chunk: assembly vectorizes — one stripe
        pass collects buffer references, one batched shifted-gather fills
        ``out``, one contiguous copy per row stages."""
        L = self.length
        hits: dict[int, tuple[np.ndarray, int]] = {}
        if overlay:
            missing = []
            for i, key in keyed:
                ent = overlay.get(key)
                if ent is not None:
                    hits[i] = ent
                else:
                    missing.append((i, key))
        else:
            missing = keyed
        if missing:
            hits.update(self._lookup_refs(missing))
        if hits:
            # shift-left ring, batched: rows 1..L-1 of each prior buffer
            # land at 0..L-2; the newest transaction is already at L-1
            hi = np.fromiter(hits.keys(), np.intp, len(hits))
            out[hi, : L - 1] = np.stack([hits[i][0] for i in hi])[:, 1:]
        staged: dict[Any, tuple[np.ndarray, int, int | None]] = {}
        for i, key in keyed:
            ent = hits.get(i)
            filled = min((ent[1] if ent is not None else 0) + 1, L)
            # base = the stamp of the store entry this staging derives
            # from (None for a fresh key): commit's optimistic check
            staged[key] = (out[i].copy(), filled,
                           ent[2] if ent is not None else None)
            filled_out[i] = filled
        return staged

    def _prepare_general(self, ids, rows, out, filled_out, overlay) -> dict:
        """Duplicate keys in the chunk: the per-row loop (earlier
        same-chunk rows must be visible to later assemblies), with store
        lookups still batched per stripe up front."""
        L = self.length
        seen: dict[Any, int] = {}
        firsts = []
        for i, key in enumerate(ids):
            if key is not None and key not in seen:
                seen[key] = i
                firsts.append((i, key))
        refs_by_row = self._lookup_refs(firsts)
        refs = {ids[i]: ent for i, ent in refs_by_row.items()}
        staged: dict[Any, tuple[np.ndarray, int, int | None]] = {}
        for i, key in enumerate(ids):
            if key is None:
                continue  # cold context + this row, already assembled
            ent = staged.get(key)
            if ent is None and overlay is not None:
                o = overlay.get(key)
                if o is not None:  # earlier chunk's staged copy keeps its
                    ent = (o[0].copy(), o[1], o[2])  # original base stamp
            if ent is None:
                r = refs.get(key)
                if r is None:
                    buf = np.zeros((L, self.num_features), np.float32)
                    filled, base = 0, None
                else:  # copy-on-write: the live buffer stays untouched
                    buf, filled, base = r[0].copy(), r[1], r[2]
            else:
                buf, filled, base = ent
            buf[:-1] = buf[1:]
            buf[-1] = rows[i]
            filled = min(filled + 1, L)
            if key in staged:  # recency = LAST occurrence (see score())
                del staged[key]
            staged[key] = (buf, filled, base)
            out[i] = buf
            filled_out[i] = filled
        return staged

    # -- publication --------------------------------------------------------
    # ccfd-lint: hot-path
    def commit(self, token: tuple) -> bool:
        """Publish a prepared chunk (call only after every dispatch of the
        batch resolved). Evicts the globally-coldest keys past the cap.
        Returns False — and changes nothing — when the store was restored
        since the prepare (stale generation: the rewound bus will
        re-drive those records onto the restored state).

        Per-key optimistic check: each staged entry carries the stamp of
        the store entry it derives from; a key whose live entry moved
        since the prepare (a CONCURRENT batch committed it — e.g. a
        watchdog-abandoned dispatch's late commit racing the worker's
        next batch on the same partition keys) is SKIPPED rather than
        clobbering the newer state, counted in ``contended_skips``. The
        skipped batch's appends are recovered by the next crash-restore
        replay (the records are in the routed stream)."""
        gen, staged = token[0], token[1]
        if not staged:
            return True
        with self._commit_lock:
            if gen != self._gen:
                return False
            # stamps follow the batch's ARRIVAL order (staged dicts
            # preserve first-occurrence order), assigned BEFORE the
            # per-stripe insertion pass: stamping inside that pass would
            # make whole stripe-groups "newest" within a batch, and under
            # a binding cap eviction would systematically keep one hash
            # class of each batch (found by the replay drill: disjoint
            # survivor sets before/after a rewind)
            by_stripe: dict[int, list] = {}
            for key, ent in staged.items():
                by_stripe.setdefault(hash(key) % self.stripes, []).append(
                    (key, ent, self._stamp()))
            added = 0
            for si, items in by_stripe.items():
                st = self._stripes[si]
                with st.lock:
                    h = st.h
                    for key, (buf, filled, base), stamp in items:
                        cur = h.get(key)
                        if cur is not None and (base is None
                                                or cur[2] != base):
                            # live entry moved since this prepare: a
                            # concurrent batch owns the newer state
                            self._contended += 1
                            continue
                        if cur is not None:
                            h.move_to_end(key)
                        else:
                            added += 1
                        h[key] = (buf, filled, stamp)
                    st.dirty = True
            if added:
                with self._count_lock:
                    self._total += added
            self._evict_over_cap()
        return True

    def _evict_over_cap(self) -> None:
        """Pop the globally-oldest entry until under the cap. Runs under
        the commit lock (single evictor); takes one stripe lock at a time
        — the scan reads each stripe's LRU head stamp, the pop re-checks
        under the chosen stripe's lock."""
        while True:
            with self._count_lock:
                if self._total <= self.max_customers:
                    return
            best_i, best_stamp = -1, None
            for i, st in enumerate(self._stripes):
                with st.lock:
                    if st.h:
                        stamp = next(iter(st.h.values()))[2]
                        if best_stamp is None or stamp < best_stamp:
                            best_i, best_stamp = i, stamp
            if best_i < 0:
                return
            st = self._stripes[best_i]
            with st.lock:
                if st.h:
                    st.h.popitem(last=False)
                    st.dirty = True
                    with self._count_lock:
                        self._total -= 1

    # -- checkpoint surface (pipeline state, like the engine) ---------------
    def snapshot(self) -> dict:
        """State for the recovery coordinator's cut: runs under the
        checkpoint barrier. Stripe-incremental and ZERO-copy: a stripe
        untouched since the last snapshot reuses its cached entry list,
        and entries share the live buffers (immutable by convention — the
        store replaces, never mutates them), so the barrier cost is
        proportional to churn, not store size. The coordinator
        JSON-normalizes outside the barrier (recovery.py _np_jsonable);
        ``restore`` accepts either form. Entries are ordered coldest
        first (global touch stamps), so a restore rebuilds the same
        eviction order."""
        with self._commit_lock:
            entries: list[tuple[int, Any, np.ndarray, int]] = []
            for st in self._stripes:
                with st.lock:
                    if st.dirty:
                        st.cache = [
                            (stamp, key, buf, filled)
                            for key, (buf, filled, stamp) in st.h.items()
                        ]
                        st.dirty = False
                    entries.extend(st.cache)
            entries.sort(key=lambda e: e[0])
            return {
                "version": 1,
                "length": self.length,
                "num_features": self.num_features,
                "customers": [[key, buf, filled]
                              for _, key, buf, filled in entries],
            }

    def restore(self, snap: dict | None) -> None:
        """Replace the store's content with a snapshot's (crash recovery:
        the rewound bus re-drives post-cut records, re-building exactly
        the histories the cut had). ``None`` resets to empty (genesis
        restore — replay from offset 0 rebuilds everything). The
        generation bumps LAST, so a prepare racing this call either sees
        the old generation (its commit is dropped) or the fully-restored
        state."""
        with self._commit_lock:
            for st in self._stripes:
                with st.lock:
                    st.h.clear()
                    st.dirty = True
                    st.cache = []
            total = 0
            if snap is not None:
                if snap.get("version") != 1:
                    raise ValueError(
                        f"unknown history snapshot {snap.get('version')!r}")
                if (int(snap["length"]) != self.length
                        or int(snap["num_features"]) != self.num_features):
                    raise ValueError("history snapshot shape mismatch")
                for key, buf, filled in snap["customers"]:
                    st = self._stripe_of(key)
                    with st.lock:
                        st.h[key] = (
                            np.asarray(buf, np.float32).reshape(
                                self.length, self.num_features),
                            int(filled),
                            self._stamp(),
                        )
                    total += 1
            with self._count_lock:
                self._total = total
            self._gen += 1  # in-flight prepares become stale commits

    @property
    def contended_skips(self) -> int:
        return self._contended

    def snapshot_counts(self) -> dict:
        return {"customers": len(self), "length": self.length,
                "stripes": self.stripes}


class SeqScorer:
    """History-aware scorer with the row scorer's serving discipline —
    bucketed static shapes — run as an overlapped dataflow: per-(L, B)
    bucket dispatches enqueue asynchronously while the next group
    assembles, bounded by ``inflight``; ONE commit per router batch after
    every dispatch resolved (see module docstring)."""

    def __init__(
        self,
        params: Any,
        length: int = 64,
        batch_sizes: tuple = (16, 128, 1024, 4096),
        compute_dtype: str = "bfloat16",
        max_customers: int = 20_000,
        registry: Any = None,
        mesh: Any = None,
        stripes: int = DEFAULT_STRIPES,
        inflight: int = DEFAULT_INFLIGHT,
        len_buckets: tuple | None = None,
        telemetry: Any = None,
        partitioner: Any = None,
        seq_parallel: str = "none",
    ):
        """``mesh``: serve the seq dispatch over a device mesh — history
        batches split over the partitioned axes, params replicated (the
        same SPMD layout the row Scorer's data-axis path uses; history
        ASSEMBLY stays host-side either way). Bucket sizes round up to
        axis-size multiples so every shard gets identical static shapes.
        ``partitioner`` (parallel/partition.py): the first-class form of
        the same — supplies the mesh, the PARAM layout (the regex rule
        table under ``param_partition: rules``, replicated under data
        parallel; an uncovered tree such as the int8 seq_q8 variant
        replicates with a warning) and the publish path.

        ``seq_parallel``: ``none`` | ``ring`` | ``ulysses`` — shard the
        attention's L dim over the mesh's ``tp`` (or legacy ``model``)
        axis (ops/ring_attention.py / ops/ulysses.py). The previously
        dormant flag, now operator-selectable (CR ``mesh.seq_parallel``).
        Blocks whose static shapes can't shard (the readout block's
        single-query attention; an L bucket not divisible by the axis)
        fall back to reference attention per-executable — shapes are
        static at trace time, so the choice costs nothing at runtime.

        ``inflight``: async dispatches in flight before the loop blocks
        on the oldest (0 = resolve immediately, the synchronous path).
        ``len_buckets``: the short-sequence ladder; the full ``length``
        is always appended. A row dispatches at the smallest bucket
        covering its post-append history depth."""
        import jax
        import jax.numpy as jnp

        self.store = HistoryStore(length=length, max_customers=max_customers,
                                  stripes=stripes)
        # device telemetry plane (observability/device.py): the seq
        # dispatch ships (B, L, F) history batches whose transfer happens
        # INSIDE the jitted call, so only the bytes are separately
        # countable here (ccfd_h2d_bytes_total); the row scorer's explicit
        # staging carries the timed samples
        if telemetry is None:
            from ccfd_tpu.observability import device as _device

            telemetry = _device.get_default()
        self.telemetry = telemetry
        self._dtype = (jnp.bfloat16 if compute_dtype == "bfloat16"
                       else jnp.float32)
        self.inflight = max(0, int(inflight))
        if len_buckets is None:
            len_buckets = DEFAULT_LEN_BUCKETS
        self.len_buckets = tuple(sorted(
            {int(b) for b in len_buckets if 0 < int(b) < length}
            | {int(length)}))
        self.partitioner = partitioner
        if partitioner is not None:
            mesh = partitioner.mesh
        self.mesh = mesh
        self.seq_parallel = str(seq_parallel or "none").lower()
        if self.seq_parallel not in ("none", "ring", "ulysses"):
            raise ValueError(
                f"seq_parallel={seq_parallel!r}: expected none|ring|ulysses")
        self._batch_sharding = None
        self._part_axes = None
        self._sp_axis = None
        # trace-time seq-parallel engagement tally (_sp_attention): did
        # the configured mode ever actually shard an attention block?
        self._sp_engaged = 0
        self._sp_fallback = 0
        self._sp_warned = False
        if self.seq_parallel != "none" and mesh is None:
            raise ValueError("seq_parallel needs a mesh")
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            if self.seq_parallel != "none":
                # L shards over the tensor-parallel axis (named mesh
                # "tp"; legacy 2-D mesh "model") — the batch must NOT
                # also split over it
                for a in ("tp", "model"):
                    if mesh.shape.get(a, 1) > 1:
                        self._sp_axis = a
                        break
                if self._sp_axis is None:
                    raise ValueError(
                        f"seq_parallel={self.seq_parallel!r} needs a "
                        f"tp/model mesh axis of size > 1; mesh axes are "
                        f"{dict(mesh.shape)}")
            # split the batch over EVERY non-sp axis the mesh has: the
            # data axis alone would idle the other devices on a
            # replicated-param elementwise path, and naming an axis the
            # mesh lacks (e.g. a data-only mesh) would raise
            part_axes = tuple(
                a for a in ("data", "fsdp", "tp", "model")
                if mesh.shape.get(a, 1) > 1 and a != self._sp_axis) \
                or tuple(a for a in mesh.axis_names
                         if a != self._sp_axis)[:1]
            dsize = 1
            for a in part_axes:
                dsize *= mesh.shape[a]
            batch_sizes = tuple(
                max(1, -(-b // dsize)) * dsize for b in batch_sizes
            )
            self._part_axes = part_axes
            # param layout: the partitioner's (rule table under `rules`,
            # replicated under dp); legacy bare-mesh callers replicate
            params = jax.device_put(params, self._param_layout(params))
            self._batch_sharding = NamedSharding(
                mesh, PartitionSpec(part_axes, None, None))
        self.params = params
        self.batch_sizes = tuple(sorted(set(batch_sizes)))
        self._jax = jax
        self._quantized = self._is_quantized(params)
        self._apply = self._make_apply(self._quantized)
        self._params_lock = threading.Lock()
        # challenger slot (lifecycle/): a second params tree + jit scored
        # off the hot path by the shadow tap's worker — how the seq_q8
        # variant earns its AUC/PSI verdict before it may serve
        self._challenger: tuple[int, Any, Any] | None = None
        # shadow tap + canary gate (lifecycle/): the router calls
        # score_with_ids on this OBJECT, so there is no score_fn lane to
        # wrap — when armed, each resolved chunk offers its (hist, proba)
        # pair to the tap, and an active canary gate re-scores its
        # deterministic challenger slice against the same assembled
        # contexts (the seq analog of tap-inside/gate-outside)
        self.shadow_tap: Any = None
        self.canary_gate: Any = None
        self._swap_gate: Any = None  # partitioner publish gate (set_swap_gate)
        self._g_customers = None
        self._h_assembly = self._h_dispatch = None
        self._c_bucket = self._c_bucket_rows = None
        self._g_inflight = self._c_anon = self._c_stale = None
        if registry is not None:
            self._g_customers = registry.gauge(
                "seq_history_customers", "customers with live history"
            )
            self._h_assembly = registry.histogram(
                "seq_assembly_seconds",
                "host-side history assembly time per router batch "
                "(prepare + L/B bucketing + padding)",
            )
            self._h_dispatch = registry.histogram(
                "seq_dispatch_seconds",
                "device dispatch time per router batch: enqueue plus the "
                "blocking waits the overlap could not hide",
            )
            self._c_bucket = registry.counter(
                "seq_bucket_dispatch_total",
                "seq dispatches by (L bucket, B bucket) executable",
            )
            self._c_bucket_rows = registry.counter(
                "seq_bucket_rows_total",
                "rows scored per L bucket (short buckets = the cold-row "
                "fast lane actually firing)",
            )
            self._g_inflight = registry.gauge(
                "seq_inflight_dispatches",
                "async seq dispatches currently in flight",
            )
            self._c_anon = registry.counter(
                "seq_anonymous_rows_total",
                "anonymous rows scored cold (lock-free prepare fast path; "
                "never stored)",
            )
            self._c_stale = registry.counter(
                "seq_stale_commits_total",
                "commits dropped for stale generation (dispatch in flight "
                "across a crash restore — the no-op that keeps replay "
                "from double-appending)",
            )

    # -- variant dispatch ---------------------------------------------------
    @staticmethod
    def _is_quantized(params: Any) -> bool:
        from ccfd_tpu.ops import seq_quant

        return seq_quant.is_quantized(params)

    def _sp_attention(self):
        """The operator-selected sequence-parallel attention (ring /
        ulysses over the sp axis), or None. Static-shape gated: the
        readout block's single-query attention and any L bucket the axis
        doesn't divide (ulysses additionally: a head count it doesn't
        divide) take reference attention for that executable — decided at
        trace time, free at runtime. Engagement is TRACKED at trace time
        (``_sp_engaged``/``_sp_fallback``) so the executable inventory
        reports whether the configured mode ever actually sharded an
        attention block, and an all-fallback config warns loudly instead
        of silently serving unsharded under a ``seq_parallel`` label."""
        if self._sp_axis is None:
            return None
        mesh, axis = self.mesh, self._sp_axis
        n = int(mesh.shape[axis])
        if self.seq_parallel == "ring":
            from ccfd_tpu.ops.ring_attention import ring_attention as sp_fn
        else:
            from ccfd_tpu.ops.ulysses import ulysses_attention as sp_fn
        needs_heads = self.seq_parallel == "ulysses"

        def attn(q, k, v):
            shardable = (
                q.shape[2] == k.shape[2]      # not the readout query
                and q.shape[2] % n == 0       # L divides the axis
                and (not needs_heads or q.shape[1] % n == 0)
            )
            if not shardable:
                # trace-time accounting: this executable's block falls
                # back (the readout query always does — only warn when a
                # FULL-attention block can't shard, which means the
                # configured mode never engages for that shape)
                self._sp_fallback += 1
                if q.shape[2] == k.shape[2] and not self._sp_warned:
                    self._sp_warned = True
                    import logging

                    logging.getLogger(__name__).warning(
                        "seq_parallel=%s cannot shard a (heads=%d, L=%d)"
                        " attention over the %d-way %r axis; that "
                        "executable serves reference attention",
                        self.seq_parallel, q.shape[1], q.shape[2], n,
                        axis)
                from ccfd_tpu.ops.ring_attention import reference_attention

                return reference_attention(q, k, v)
            self._sp_engaged += 1
            return sp_fn(q, k, v, mesh, axis)

        return attn

    def _make_apply(self, quantized: bool):
        import jax

        from ccfd_tpu.models import seq as seq_mod
        from ccfd_tpu.ops import seq_quant

        dtype = self._dtype
        # positional encodings anchor at the store's FULL length: a short
        # L-bucket window's tokens keep the positions the full-L path
        # gives them, so a customer's score doesn't jump at ladder
        # crossovers (models/seq.py logits_readout pos_length)
        plen = self.store.length
        if self.mesh is None:
            if quantized:
                return lambda p, xs: seq_quant.apply_serving(
                    p, xs, dtype, pos_length=plen)
            return lambda p, xs: seq_mod.apply_serving(
                p, xs, dtype, pos_length=plen)
        from jax.sharding import NamedSharding, PartitionSpec

        fn = seq_quant.logits if quantized else seq_mod.logits_readout
        attn = self._sp_attention()
        return jax.jit(
            lambda p, xs: jax.nn.sigmoid(
                fn(p, xs, dtype, attention_fn=attn, pos_length=plen)),
            out_shardings=NamedSharding(self.mesh,
                                        PartitionSpec(self._part_axes)),
        )

    def _put_hist(self, hist: np.ndarray):
        """H2D with placement: on a mesh each device gets its row shard.
        Shares the staging fault seam with the row scorer's _put_batch
        (runtime/faults.py put_fail): an injected staging failure rides
        the same exception path a real transfer failure would."""
        try:
            device_seam("put")
        except Exception:
            if self.telemetry is not None:
                self.telemetry.record_h2d_failure()
            raise
        if self._batch_sharding is None:
            return hist
        return self._jax.device_put(hist, self._batch_sharding)

    def set_swap_gate(self, gate: Any) -> None:
        """Arm the partitioner's publish gate (parallel/partition.py):
        every ``swap_params`` then pauses the router pool at a batch
        boundary first — same contract as the row Scorer's."""
        self._swap_gate = gate

    def _param_layout(self, params: Any) -> Any:
        """Sharding pytree for the seq params on the mesh: the
        partitioner's layout when one is armed (the rule table under
        ``param_partition: rules``, replicated under data parallel);
        a tree the rule table does not cover — the promoted int8
        ``seq_q8`` variant has its own leaf names — replicates with a
        LOUD warning rather than crashing the promotion swap (the int8
        tree is 4x smaller, so replication is the sane fallback)."""
        from ccfd_tpu.parallel.sharding import replicated

        if self.partitioner is None:
            return replicated(self.mesh)
        try:
            return self.partitioner.param_sharding(params)
        except ValueError as e:
            import logging

            logging.getLogger(__name__).warning(
                "seq param layout: rule table does not cover this tree "
                "(%s); replicating instead", e)
            return replicated(self.mesh)

    def swap_params(self, params: Any) -> None:
        """Hot-swap model weights (the lifecycle promotion surface; the
        row scorer exposes the same). A variant change — bf16 champion
        replaced by a promoted int8 ``seq_q8`` tree, or back — re-binds
        the jitted apply; same-variant swaps reuse the jit cache (same
        treedef, same executable). All staging (mesh re-layout, variant
        grid precompile) happens BEFORE the publish gate: with a gate
        armed the router pool quiesces only for the reference flip."""
        staged, quantized, new_apply = self._stage_swap(params)
        gate = getattr(self, "_swap_gate", None)
        if gate is None:
            self._commit_swap(staged, quantized, new_apply)
            return
        with gate:
            self._commit_swap(staged, quantized, new_apply)

    def _stage_swap(self, params: Any) -> tuple:
        if self.mesh is not None:
            params = self._jax.device_put(params,
                                          self._param_layout(params))
        quantized = self._is_quantized(params)
        new_apply = None
        if quantized != self._quantized:
            # variant change (e.g. a promoted seq_q8): compile the whole
            # (B, L) executable grid BEFORE publishing — scoring keeps the
            # old graph meanwhile, so the hot path never pays an XLA
            # compile (which could outlive the dispatch watchdog deadline
            # and roll back the candidate that was just promoted)
            from ccfd_tpu.observability.profile import compile_stage

            new_apply = self._make_apply(quantized)
            with compile_stage("seq.swap"):
                for b in self.batch_sizes:
                    for lb in self.len_buckets:
                        xs = np.zeros((b, lb, self.store.num_features),
                                      np.float32)
                        self._jax.block_until_ready(
                            new_apply(params, self._put_hist(xs)))
        return params, quantized, new_apply

    def _commit_swap(self, params: Any, quantized: bool,
                     new_apply: Any) -> None:
        with self._params_lock:
            self.params = params
            if new_apply is not None:
                self._quantized = quantized
                self._apply = new_apply

    def warmup(self) -> None:
        """Compile every (B bucket, L bucket) executable the ladder can
        dispatch — the re-trace-stable static shape set."""
        from ccfd_tpu.observability.profile import compile_stage

        with compile_stage("seq.warmup"):
            for b in self.batch_sizes:
                for lb in self.len_buckets:
                    xs = np.zeros((b, lb, self.store.num_features),
                                  np.float32)
                    self._jax.block_until_ready(
                        self._apply(self.params, self._put_hist(xs)))

    def executable_grid(self) -> dict:
        """The (L, B) executable grid with per-executable dispatch counts
        — the seq family's entry in the device telemetry inventory."""
        grid = []
        for lb in self.len_buckets:
            for b in self.batch_sizes:
                entry: dict = {"l_bucket": int(lb), "b_bucket": int(b)}
                if self._c_bucket is not None:
                    entry["dispatches"] = int(self._c_bucket.value(
                        {"l_bucket": str(lb), "b_bucket": str(b)}))
                grid.append(entry)
        out = {
            "model": "seq_q8" if self._quantized else "seq",
            "length": int(self.store.length),
            "grid": grid,
        }
        if self.mesh is not None:
            out["mesh_devices"] = int(self.mesh.size)
            out["seq_parallel"] = self.seq_parallel
            if self.seq_parallel != "none":
                # truthful telemetry: configured is not engaged — an
                # operator debugging a missing sp speedup reads whether
                # any traced executable actually sharded its attention
                out["seq_parallel_engaged"] = self._sp_engaged > 0
        return out

    def _bucket(self, n: int) -> int:
        for b in self.batch_sizes:
            if n <= b:
                return b
        return self.batch_sizes[-1]

    def _len_bucket_index(self, filled: np.ndarray) -> np.ndarray:
        """Per-row ladder index: smallest L bucket covering the row's
        post-append history depth."""
        return np.searchsorted(np.asarray(self.len_buckets), filled,
                               side="left")

    # -- the overlapped scoring loop ---------------------------------------
    def score(self, x: np.ndarray, ids: list | None = None) -> np.ndarray:
        """Router-compatible scorer: (B, F) rows -> (B,) probabilities,
        each conditioned on that customer's history. Rows with no id
        (``ids`` absent or None entries) score against an empty history
        and are not tracked.

        ONE commit for the whole router batch, after EVERY dispatch
        resolved: a mid-batch failure drops the batch at the router (or
        the PR 6 dispatch watchdog kills it), and a half-committed
        history would diverge from the routed stream. The overlay keeps
        same-customer visibility across chunks; the generation token
        makes a commit that raced a crash restore a no-op (the rewind
        re-drives those records)."""
        n = len(x)
        if n == 0:
            return np.zeros((0,), np.float32)
        if ids is None:
            ids = [None] * n
        out = np.empty((n,), np.float32)
        largest = self.batch_sizes[-1]
        L = self.store.length
        ladder = self.len_buckets
        merged: dict = {}
        gen = None
        pending: deque = deque()  # (device array, global row idx, m)
        # shadow/canary lane: when a challenger is armed (tap) or a
        # canary slice is live (gate), keep each chunk's assembled
        # (full-L) history batch so the challenger scores the SAME
        # contexts the champion just did (one flag read when idle)
        tap = self.shadow_tap
        if tap is not None and tap.armed_version is None:
            tap = None
        gate = self.canary_gate
        if gate is not None and not gate.active:
            gate = None
        tap_chunks: list[tuple[np.ndarray, int, int]] = []
        keep_hist = tap is not None or gate is not None
        t_asm = 0.0
        t_disp = 0.0
        n_anon = 0
        start = 0
        while start < n:
            stop = min(start + largest, n)
            t0 = time.perf_counter()
            chunk_ids = ids[start:stop]
            hist, (chunk_gen, staged, filled) = self.store.prepare(
                chunk_ids, x[start:stop], overlay=merged
            )
            # the FIRST chunk's generation stamps the whole batch: a
            # restore landing between chunk prepares bumps the store's
            # generation, and committing with a later chunk's (fresh) gen
            # would publish the earlier chunks' pre-restore staging onto
            # the restored state — the first gen is stale then, so the
            # commit is the no-op replay correctness requires
            if gen is None:
                gen = chunk_gen
            # recency = LAST occurrence: a key re-staged by a later chunk
            # moves to the end of merged, so commit stamps (and therefore
            # LRU eviction under a binding cap) follow stream order, not
            # first-touch order — replay with different batch boundaries
            # must rebuild the same survivor set
            for k in staged:
                if k in merged:
                    del merged[k]
            merged.update(staged)
            n_anon += chunk_ids.count(None)
            li = self._len_bucket_index(filled)
            if keep_hist:
                tap_chunks.append((hist, start, stop))
            t_asm += time.perf_counter() - t0
            for bi in np.unique(li):
                lb = ladder[bi]
                idx = np.nonzero(li == bi)[0]
                # greedy B decomposition: a group between bucket sizes
                # dispatches as exact-fit sub-batches (1229 -> 1024 + 128
                # + 128-padded-77) instead of one bucket padded to 3x the
                # rows — padding is wasted device compute, and with async
                # dispatch the extra launches pipeline instead of queuing
                pos = 0
                m_total = len(idx)
                while pos < m_total:
                    t0 = time.perf_counter()
                    rem = m_total - pos
                    bucket = None
                    for b in reversed(self.batch_sizes):
                        if b <= rem:
                            bucket = b
                            break
                    if bucket is None:
                        bucket = self.batch_sizes[0]
                    m = min(rem, bucket)
                    sub_idx = idx[pos:pos + m]
                    pos += m
                    if lb == L and m == len(hist):
                        sub = hist
                    else:  # right-aligned window
                        sub = hist[sub_idx, L - lb:, :]
                    if m < bucket:
                        sub = np.concatenate(
                            [sub, np.zeros((bucket - m, *sub.shape[1:]),
                                           np.float32)]
                        )
                    with self._params_lock:
                        params, apply_fn = self.params, self._apply
                    t_asm += time.perf_counter() - t0
                    t0 = time.perf_counter()
                    # device-fault dispatch seam (runtime/faults.py):
                    # device_hang / compile_stall drill the heal ladder
                    # through the seq path's own dispatch loop
                    device_seam("dispatch")
                    # JAX async dispatch: the call ENQUEUES the executable
                    # and returns; the next group assembles while it runs.
                    dev = apply_fn(params, self._put_hist(sub))
                    t_disp += time.perf_counter() - t0
                    if self.telemetry is not None:
                        self.telemetry.record_h2d(sub.nbytes)
                    pending.append((dev, sub_idx + start, m))
                    if self._c_bucket is not None:
                        self._c_bucket.inc(labels={
                            "l_bucket": str(lb), "b_bucket": str(bucket)})
                        self._c_bucket_rows.inc(
                            m, labels={"l_bucket": str(lb)})
                    if self._g_inflight is not None:
                        self._g_inflight.set(float(len(pending)))
                    while len(pending) > self.inflight:
                        t_disp += self._resolve(pending, out)
            start = stop
        while pending:
            t_disp += self._resolve(pending, out)
        if gen is not None:
            if not self.store.commit((gen, merged)):
                if self._c_stale is not None:
                    self._c_stale.inc()
        if tap is not None:
            # the tap pairs PURE champion scores (offered before any
            # canary override, like the row lane's tap-inside/gate-outside
            # composition)
            for hist, s0, s1 in tap_chunks:
                tap.offer(hist, out[s0:s1])
        if gate is not None and tap_chunks:
            # canary slice: the challenger arm re-scores against the SAME
            # assembled contexts (bounded by the gate's weight; a
            # challenger failure keeps champion scores and counts)
            def rescore(mask: np.ndarray) -> np.ndarray:
                parts = [h[mask[s0:s1]] for h, s0, s1 in tap_chunks]
                sel = parts[0] if len(parts) == 1 else np.concatenate(parts)
                return self.challenger_score(sel)

            out = gate.apply(np.ascontiguousarray(x, np.float32), out,
                             rescore=rescore)
        if self._g_customers is not None:
            self._g_customers.set(float(len(self.store)))
        if self._h_assembly is not None:
            self._h_assembly.observe(t_asm)
            self._h_dispatch.observe(t_disp)
        if n_anon and self._c_anon is not None:
            self._c_anon.inc(n_anon)
        return out

    def _resolve(self, pending: deque, out: np.ndarray) -> float:
        """Block on the oldest in-flight dispatch and scatter its rows;
        returns the blocking wait (the dispatch time overlap failed to
        hide)."""
        dev, idx, m = pending.popleft()
        t0 = time.perf_counter()
        proba = np.asarray(dev)
        dt = time.perf_counter() - t0
        out[idx] = proba[:m]
        if self._g_inflight is not None:
            self._g_inflight.set(float(len(pending)))
        return dt

    # Router contract: passing the SeqScorer OBJECT as the router's
    # score_fn makes it callable for the plain (x,) path, and the router
    # detects score_with_ids and feeds decoded records alongside x
    def __call__(self, x: np.ndarray) -> np.ndarray:
        return self.score(x)

    def score_with_ids(self, txs: list, x: np.ndarray) -> np.ndarray:
        """Batch entry for the router: ids come from each record's
        ``customer_id``/``id`` field; records with neither are anonymous
        (scored cold, not tracked). When the shadow tap is armed,
        ``score`` offers each chunk's assembled history batch alongside
        the champion's probabilities — the challenger shadow-scores the
        SAME contexts."""
        ids: list = []
        for t in txs:
            key = None
            if isinstance(t, dict):
                key = t.get("customer_id")
                if key is None:
                    key = t.get("id")
            ids.append(key)
        return self.score(x, ids)

    # -- challenger slot (model lifecycle: shadow scoring of seq_q8) --------
    def install_challenger(self, version: int, params: Any) -> None:
        """Stage a challenger (typically the int8 ``seq_q8`` tree) beside
        the champion. Challenger forwards run on the shadow tap's worker
        thread against cold contexts or tapped batches — sample-bounded
        by the tap's token bucket, so the hot path never waits on it."""
        fn = self._make_challenger_apply(params)
        with self._params_lock:
            self._challenger = (int(version), params, fn)

    def _make_challenger_apply(self, params: Any):
        from ccfd_tpu.models import seq as seq_mod
        from ccfd_tpu.ops import seq_quant

        dtype = self._dtype
        plen = self.store.length
        if self._is_quantized(params):
            return lambda p, xs: seq_quant.apply_serving(
                p, xs, dtype, pos_length=plen)
        return lambda p, xs: seq_mod.apply_serving(
            p, xs, dtype, pos_length=plen)

    def clear_challenger(self, version: int | None = None) -> None:
        with self._params_lock:
            if (self._challenger is not None
                    and (version is None
                         or self._challenger[0] == int(version))):
                self._challenger = None

    @property
    def challenger_version(self) -> int | None:
        ch = self._challenger
        return None if ch is None else ch[0]

    def challenger_score(self, x: np.ndarray) -> np.ndarray:
        """(n, F) rows (scored against a COLD context — the evaluator's
        label joins carry no history) or (n, L', F) histories (tapped
        batches) -> (n,) proba on the challenger params."""
        ch = self._challenger
        if ch is None:
            raise RuntimeError("no challenger installed")
        _, params, fn = ch
        return self._score_direct(np.asarray(x, np.float32), params, fn,
                                  put=lambda h: h)

    def host_score(self, x: np.ndarray) -> np.ndarray:
        """Champion cold-context scoring for (n, F) rows — the paired
        half of the evaluator's label join (same rows, same cold
        context, champion vs challenger)."""
        with self._params_lock:
            params, fn = self.params, self._apply
        return self._score_direct(np.asarray(x, np.float32), params, fn,
                                  put=self._put_hist)

    def _score_direct(self, x: np.ndarray, params: Any, fn, put) -> np.ndarray:
        if x.ndim == 2:
            lb = self.len_buckets[0]
            h = np.zeros((len(x), lb, self.store.num_features), np.float32)
            h[:, -1] = x
            x = h
        n = len(x)
        out = np.empty((n,), np.float32)
        largest = self.batch_sizes[-1]
        start = 0
        while start < n:
            stop = min(start + largest, n)
            m = stop - start
            sub = x[start:stop]
            bucket = self._bucket(m)
            if m < bucket:
                sub = np.concatenate(
                    [sub, np.zeros((bucket - m, *sub.shape[1:]), np.float32)]
                )
            proba = np.asarray(fn(params, put(np.ascontiguousarray(sub))))
            out[start:stop] = proba[:m]
            start = stop
        return out
