"""HTTP client for a remote prediction server (Seldon-contract).

The reference router and KIE server call Seldon over REST with a pooled
HTTP client configured by ``SELDON_URL``/``SELDON_ENDPOINT``/``SELDON_TOKEN``
/``SELDON_TIMEOUT``/``SELDON_POOL_SIZE`` (reference deploy/router.yaml:65-68,
README.md:370-402). This client reproduces that contract over stdlib
``http.client`` with a bounded connection pool, so the router/process-engine
can run on a different host than the TPU scorer. Returned as a plain
``score_fn(np (B,30)) -> np (B,)`` so it is interchangeable with the
in-process ``Scorer.score`` everywhere.
"""

from __future__ import annotations

import http.client
import json
import queue
import sys
import time
import urllib.parse
from typing import Any

import numpy as np

from ccfd_tpu.config import Config
from ccfd_tpu.data.ccfd import FEATURE_NAMES


class SeldonClient:
    def __init__(self, cfg: Config, breaker=None, faults=None, tracer=None):
        self.cfg = cfg
        # observability/trace.py: each predict POST becomes an rpc.scorer
        # client span and carries traceparent, so the remote
        # PredictionServer's serving.predict span joins the router's trace
        self._tracer = tracer
        u = urllib.parse.urlparse(cfg.seldon_url)
        if u.scheme not in ("http", ""):
            raise ValueError(f"unsupported scheme in SELDON_URL: {cfg.seldon_url!r}")
        self._host = u.hostname or "localhost"
        self._port = u.port or 80
        self._path = "/" + cfg.seldon_endpoint.lstrip("/")
        self._timeout = cfg.seldon_timeout_ms / 1000.0
        # per-edge resilience (runtime/breaker.py, runtime/faults.py): an
        # open breaker refuses instantly instead of eating SELDON_TIMEOUT
        # per call against a dead scorer — the router's degradation ladder
        # is what catches the refusal
        self._breaker = breaker
        self._faults = faults
        import random

        self._rng = random.Random(0)  # deterministic backoff jitter
        self._pool: "queue.Queue[http.client.HTTPConnection]" = queue.Queue()
        for _ in range(max(1, cfg.seldon_pool_size)):
            self._pool.put(self._connect())

    def _connect(self) -> http.client.HTTPConnection:
        # Nagle off: headers+body ride separate segments, and a delayed ACK
        # would stall the predict hop ~40 ms (see utils/httpclient.py)
        from ccfd_tpu.utils.httpclient import _NodelayHTTPConnection

        return _NodelayHTTPConnection(self._host, self._port, timeout=self._timeout)

    def _request(self, body: dict[str, Any]) -> dict[str, Any]:
        """POST with per-attempt SELDON_TIMEOUT and bounded retries.

        Retries (CCFD_CLIENT_RETRIES, exponential backoff with jitter —
        runtime/breaker.backoff_s) cover the window where the supervisor is
        restarting a crashed scorer — the reference has no app-level retry,
        only the timeout knob (README.md:386-393), so a scorer restart
        drops messages there. With a breaker wired, an open circuit refuses
        BEFORE dialing: a blackholed scorer costs one timeout per window,
        not one per micro-batch.
        """
        if self._breaker is not None and not self._breaker.allow():
            from ccfd_tpu.runtime.breaker import CircuitOpenError

            if self._tracer is not None:
                from ccfd_tpu.observability.trace import current_context

                # flag the CALLER's trace (breaker refusals are always
                # tail-sampled KEEP) — but only when a trace is active:
                # rooting a fresh trace per refusal would cycle the
                # retained ring with zero-length refusal traces during
                # exactly the incident window
                if current_context() is not None:
                    with self._tracer.span("rpc.scorer",
                                           attrs={"breaker_open": True}):
                        pass
            raise CircuitOpenError("circuit open for the prediction server")
        span_cm = (self._tracer.span("rpc.scorer",
                                     attrs={"path": self._path})
                   if self._tracer is not None else None)
        span_entered = False
        conn = self._pool.get()
        try:
            payload = json.dumps(body)
            headers = {"Content-Type": "application/json"}
            if self.cfg.seldon_token:
                headers["Authorization"] = f"Bearer {self.cfg.seldon_token}"
            if span_cm is not None:
                from ccfd_tpu.observability.trace import (
                    current_context,
                    format_traceparent,
                )

                span_cm.__enter__()
                span_entered = True
                headers["traceparent"] = format_traceparent(current_context())
            attempts = max(1, self.cfg.client_retries + 1)
            last_exc: Exception | None = None
            for attempt in range(attempts):
                t0 = time.monotonic()
                try:
                    corrupt = (self._faults.before()
                               if self._faults is not None else False)
                    conn.request("POST", self._path, payload, headers)
                    resp = conn.getresponse()
                    data = resp.read()
                    if resp.status != 200:
                        # ANY non-200 records a breaker failure before
                        # raising: the edge is not serving predictions,
                        # and an unrecorded outcome would leak the
                        # admitted HALF_OPEN probe slot and wedge the
                        # circuit open permanently
                        if self._breaker is not None:
                            self._breaker.record_failure(
                                time.monotonic() - t0)
                        raise RuntimeError(
                            f"prediction server returned {resp.status}: {data[:200]!r}"
                        )
                    try:
                        out = json.loads(data)
                        if self._faults is not None:
                            out = self._faults.after(out, corrupt)
                    except ValueError:
                        # undecodable 200 body: same record-before-raise
                        # rule (InjectedFault is an OSError and takes the
                        # transport-failure path below instead)
                        if self._breaker is not None:
                            self._breaker.record_failure(
                                time.monotonic() - t0)
                        raise
                    if self._breaker is not None:
                        self._breaker.record_success(time.monotonic() - t0)
                    return out
                except (http.client.HTTPException, OSError) as e:
                    # stale pooled connection or server mid-restart: reconnect
                    last_exc = e
                    if self._breaker is not None:
                        self._breaker.record_failure(time.monotonic() - t0)
                    conn.close()
                    if attempt < attempts - 1:
                        from ccfd_tpu.runtime.breaker import backoff_s

                        time.sleep(backoff_s(attempt, rng=self._rng))
                    conn = self._connect()
            raise ConnectionError(
                f"prediction server unreachable after {attempts} attempts"
            ) from last_exc
        finally:
            self._pool.put(conn)
            if span_entered:
                # closes the span with error status when an exception is
                # in flight (sys.exc_info() is live inside finally)
                span_cm.__exit__(*sys.exc_info())

    def score(self, x: np.ndarray) -> np.ndarray:
        """(B, 30) -> (B,) proba_1 via POST <SELDON_URL>/<SELDON_ENDPOINT>."""
        x = np.asarray(x, np.float32)
        out = self._request(
            {"data": {"names": list(FEATURE_NAMES), "ndarray": x.tolist()}}
        )
        nd = out["data"]["ndarray"]
        return np.asarray([row[1] for row in nd], np.float32)

    def close(self) -> None:
        while not self._pool.empty():
            try:
                self._pool.get_nowait().close()
            except queue.Empty:  # pragma: no cover
                break
