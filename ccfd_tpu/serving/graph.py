"""Seldon-shaped inference graph, compiled into ONE jitted TPU function.

The reference's model-serving layer is Seldon Core, whose unit of deployment
is an *inference graph*: a tree of typed nodes declared in a SeldonDeployment
CR (reference deploy/model/modelfull.json:18-52 — graph at 37-44 is the
single-node case ``{"name": "modelfull", "type": "MODEL", "endpoint":
{"type": "REST"}}``). Seldon's engine walks that tree at request time, one
HTTP hop per node container. Node types (Seldon Core v1 semantics):

- ``MODEL``              — scores the features
- ``TRANSFORMER``        — rewrites the input before its child sees it
- ``OUTPUT_TRANSFORMER`` — rewrites its child's output
- ``COMBINER``           — merges the outputs of >=2 children (ensembles)
- ``ROUTER``             — sends each request to one of >=2 children (A/B,
                           canary, bandits)

TPU-first redesign: the graph is *compiled*, not *walked*. ``build()``
closes the whole tree into a single ``(params, x) -> proba_1`` function that
runs under one ``jax.jit`` — every transformer/combiner fuses into the model
matmuls, and there are zero per-node network hops or host round-trips.

Routing is the interesting re-mapping. Seldon routes by picking ONE child
container per request. Under XLA that would be data-dependent control flow
with ragged per-branch batches — retrace city. Instead every branch scores
the full batch on the MXU and the router contributes per-row weights that
``select`` the result (one-hot for hard routing, arbitrary simplex for
traffic splits). For fraud-scorer-sized branches the redundant FLOPs are
noise next to the dispatch overhead they avoid, shapes stay static, and the
whole ensemble still compiles into one executable.

Params are a ``{node_name: node_params}`` dict, so online retrain can
hot-swap any node's weights through ``Scorer.swap_params`` unchanged.
``as_model_spec()`` registers the compiled graph in the model registry,
which makes a multi-node ensemble a drop-in ``CCFD_MODEL`` for the whole
serving stack (Scorer bucketing, REST server, warmup).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp

from ccfd_tpu.data.ccfd import FEATURE_NAMES
from ccfd_tpu.models.registry import ModelSpec, get_model, register_model

NODE_TYPES = ("MODEL", "TRANSFORMER", "OUTPUT_TRANSFORMER", "COMBINER", "ROUTER")

_EPS = 1e-6


def _logit(p: jax.Array) -> jax.Array:
    p = jnp.clip(p, _EPS, 1.0 - _EPS)
    return jnp.log(p) - jnp.log1p(-p)


def _feature_index(feature: Any) -> int:
    if isinstance(feature, int):
        return feature
    return FEATURE_NAMES.index(str(feature))


# --------------------------------------------------------------------------
# Component registries: implementation name -> (init, apply).
#
# init(key, config) -> params pytree ({} for stateless components).
# Transformer apply(params, x, config) -> x'              (B,F) -> (B,F)
# Output-transformer apply(params, p, config) -> p'       (B,)  -> (B,)
# Combiner apply(params, ps, config) -> p                 [(B,)]*n -> (B,)
# Router apply(params, x, config) -> weights              (B,) -> (B,n) simplex
# --------------------------------------------------------------------------

_TRANSFORMERS: dict[str, tuple[Callable, Callable]] = {}
_OUTPUT_TRANSFORMERS: dict[str, tuple[Callable, Callable]] = {}
_COMBINERS: dict[str, tuple[Callable, Callable]] = {}
_ROUTERS: dict[str, tuple[Callable, Callable]] = {}

_KIND_REGISTRY = {
    "TRANSFORMER": _TRANSFORMERS,
    "OUTPUT_TRANSFORMER": _OUTPUT_TRANSFORMERS,
    "COMBINER": _COMBINERS,
    "ROUTER": _ROUTERS,
}


def register_component(kind: str, name: str, init: Callable, apply: Callable) -> None:
    _KIND_REGISTRY[kind][name] = (init, apply)


def _no_params(key, config):
    return {}


# -- transformers ----------------------------------------------------------

def _standardize_init(key, config):
    n = len(FEATURE_NAMES)
    mean = jnp.asarray(config.get("mean", [0.0] * n), jnp.float32)
    scale = jnp.asarray(config.get("scale", [1.0] * n), jnp.float32)
    return {"mean": mean, "scale": jnp.where(scale == 0.0, 1.0, scale)}


register_component(
    "TRANSFORMER", "standardize", _standardize_init,
    lambda p, x, cfg: (x - p["mean"]) / p["scale"],
)
register_component(
    "TRANSFORMER", "identity", _no_params, lambda p, x, cfg: x
)
register_component(
    "TRANSFORMER", "clip", _no_params,
    lambda p, x, cfg: jnp.clip(
        x, float(cfg.get("lo", -1e6)), float(cfg.get("hi", 1e6))
    ),
)

# -- output transformers ---------------------------------------------------

register_component(
    "OUTPUT_TRANSFORMER", "identity", _no_params, lambda p, y, cfg: y
)
# Platt scaling: recalibrate a scorer's probabilities without retraining it.
register_component(
    "OUTPUT_TRANSFORMER", "platt",
    lambda key, cfg: {
        "a": jnp.asarray(float(cfg.get("a", 1.0)), jnp.float32),
        "b": jnp.asarray(float(cfg.get("b", 0.0)), jnp.float32),
    },
    lambda p, y, cfg: jax.nn.sigmoid(p["a"] * _logit(y) + p["b"]),
)

# -- combiners -------------------------------------------------------------

register_component(
    "COMBINER", "average", _no_params,
    lambda p, ys, cfg: jnp.mean(jnp.stack(ys), axis=0),
)
register_component(
    "COMBINER", "max", _no_params,
    lambda p, ys, cfg: jnp.max(jnp.stack(ys), axis=0),
)


def _weighted_init(key, config):
    w = config.get("weights")
    if w is None:
        raise ValueError("combiner 'weighted' needs config weights: [..]")
    w = jnp.asarray([float(v) for v in w], jnp.float32)
    return {"w": w / jnp.sum(w)}


register_component(
    "COMBINER", "weighted", _weighted_init,
    lambda p, ys, cfg: jnp.einsum("n,nb->b", p["w"], jnp.stack(ys)),
)

# -- routers ---------------------------------------------------------------


def _feature_threshold_weights(p, x, cfg):
    """Hard route: child 1 when feature > threshold else child 0 (one-hot)."""
    j = _feature_index(cfg.get("feature", "Amount"))
    hi = (x[:, j] > float(cfg.get("threshold", 0.0))).astype(jnp.float32)
    return jnp.stack([1.0 - hi, hi], axis=1)


register_component(
    "ROUTER", "feature_threshold", _no_params, _feature_threshold_weights
)


def _hash_split_init(key, config):
    w = config.get("weights")
    if w is None:
        raise ValueError("router 'hash_split' needs config weights: [..]")
    w = jnp.asarray([float(v) for v in w], jnp.float32)
    return {"cum": jnp.cumsum(w / jnp.sum(w))}


def _hash_split_weights(p, x, cfg):
    """Deterministic traffic split (A/B, canary): a cheap per-row hash of the
    features lands each request in a weight bucket, so the same transaction
    always routes to the same arm — no host RNG, no state, jit-stable.
    HIGHEST precision pins the dot to f32 accumulation on TPU too (default
    matmul precision there is bf16), keeping the compiled split
    bit-compatible with the ``hash_split_arms_numpy`` host mirror the
    lifecycle canary gate and offline audits recompute arms with."""
    h = jnp.dot(x, jnp.arange(1.0, x.shape[1] + 1.0, dtype=x.dtype) * 0.61803398875,
                precision=jax.lax.Precision.HIGHEST)
    u = jnp.mod(jnp.abs(h), 1.0)
    arm = jnp.sum(u[:, None] >= p["cum"][None, :-1], axis=1)
    return jax.nn.one_hot(arm, p["cum"].shape[0], dtype=jnp.float32)


register_component("ROUTER", "hash_split", _hash_split_init, _hash_split_weights)


def hash_split_arms_numpy(x, weights):
    """Host mirror of the ``hash_split`` ROUTER's per-row arm assignment.

    The model-lifecycle canary gate (lifecycle/controller.py) splits live
    traffic with the SAME hash the compiled router component uses, so a
    transaction lands on the same arm whether the split runs in this
    process, another process, or inside a jitted graph — the determinism
    the canary accounting depends on (test-asserted against
    ``_hash_split_weights`` under jit and across processes). Computed in
    float32 end-to-end to match the compiled component's dtype.

    ``x``: (B, F) features; ``weights``: per-arm traffic fractions.
    Returns (B,) int arm indices.
    """
    import numpy as np

    x = np.asarray(x, np.float32)
    w = np.asarray([float(v) for v in weights], np.float32)
    cum = np.cumsum(w / np.sum(w))
    vec = (np.arange(1.0, x.shape[1] + 1.0, dtype=np.float32)
           * np.float32(0.61803398875))
    u = np.mod(np.abs(x @ vec), 1.0)
    return np.sum(u[:, None] >= cum[None, :-1], axis=1).astype(np.int32)


# --------------------------------------------------------------------------
# Graph spec + compiler
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Node:
    """One node of the inference tree (reference modelfull.json:37-44 shape)."""

    name: str
    type: str
    implementation: str = ""  # component/model name; defaults to node name
    children: tuple["Node", ...] = ()
    config: Mapping[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        if self.type not in NODE_TYPES:
            raise ValueError(f"node {self.name!r}: unknown type {self.type!r}")
        n = len(self.children)
        if self.type == "MODEL" and n != 0:
            # Seldon chains MODEL->child by feeding the response forward; a
            # (B,) probability is not a feature row, so we require explicit
            # OUTPUT_TRANSFORMER nodes instead of implicit chaining.
            raise ValueError(f"MODEL node {self.name!r} must be a leaf")
        if self.type in ("TRANSFORMER", "OUTPUT_TRANSFORMER") and n != 1:
            raise ValueError(f"{self.type} node {self.name!r} needs exactly 1 child")
        if self.type in ("COMBINER", "ROUTER") and n < 2:
            raise ValueError(f"{self.type} node {self.name!r} needs >=2 children")

    @property
    def impl(self) -> str:
        return self.implementation or self.name


_GRAPH_NAMES: set[str] = set()  # registry names owned by graphs (re-register ok)


class InferenceGraph:
    """A validated node tree plus its compiled single-dispatch evaluator."""

    def __init__(self, root: Node, name: str | None = None):
        self.root = root
        self.name = name or root.name
        names: list[str] = []

        def walk(n: Node):
            names.append(n.name)
            for c in n.children:
                walk(c)

        walk(root)
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate node names in graph: {sorted(names)}")
        self.node_names = tuple(names)

        # Fail arity mismatches at load time with the node named, not at
        # warmup deep inside jit as an anonymous einsum shape error.
        def check_arity(n: Node):
            kids = len(n.children)
            if n.type == "ROUTER" and n.impl == "feature_threshold" and kids != 2:
                raise ValueError(
                    f"router {n.name!r} (feature_threshold) needs exactly 2 "
                    f"children, has {kids}"
                )
            w = n.config.get("weights")
            if (
                n.type in ("ROUTER", "COMBINER")
                and w is not None
                and len(w) != kids
            ):
                raise ValueError(
                    f"{n.type.lower()} {n.name!r}: {len(w)} weights for "
                    f"{kids} children"
                )
            for c in n.children:
                check_arity(c)

        check_arity(root)

    # -- construction ------------------------------------------------------

    @staticmethod
    def from_cr(cr: Mapping[str, Any]) -> "InferenceGraph":
        """Load from a SeldonDeployment-shaped CR dict (modelfull.json:18-52).

        Reads ``spec.predictors[0].graph``; each node is ``{name, type,
        children, parameters}`` with Seldon's ``parameters`` list of
        ``{name, value, type}`` mapped onto the component config.
        """
        try:
            graph = cr["spec"]["predictors"][0]["graph"]
        except (KeyError, IndexError, TypeError):
            graph = cr  # allow passing the bare graph dict
        name = str(
            cr.get("metadata", {}).get("name", "") if isinstance(cr, Mapping) else ""
        )
        return InferenceGraph(InferenceGraph._parse_node(graph), name=name or None)

    @staticmethod
    def from_cr_file(path: str) -> "InferenceGraph":
        with open(path) as f:
            return InferenceGraph.from_cr(json.load(f))

    @staticmethod
    def _parse_node(d: Mapping[str, Any]) -> Node:
        config: dict[str, Any] = dict(d.get("config", {}))
        for p in d.get("parameters", ()) or ():
            v = p.get("value")
            t = str(p.get("type", "STRING")).upper()
            if t == "INT":
                v = int(v)
            elif t in ("FLOAT", "DOUBLE"):
                v = float(v)
            elif t == "BOOL":
                v = str(v).lower() in ("1", "true", "yes")
            elif t == "JSON":
                v = json.loads(v) if isinstance(v, str) else v
            config[str(p["name"])] = v
        return Node(
            name=str(d["name"]),
            type=str(d.get("type", "MODEL")).upper(),
            implementation=str(d.get("implementation", "") or ""),
            children=tuple(
                InferenceGraph._parse_node(c) for c in d.get("children", ()) or ()
            ),
            config=config,
        )

    # -- params ------------------------------------------------------------

    def init(self, key: jax.Array) -> dict[str, Any]:
        """Per-node params keyed by node name (stateless nodes get ``{}``)."""
        params: dict[str, Any] = {}

        def walk(n: Node, key):
            key, sub = jax.random.split(key)
            if n.type == "MODEL":
                params[n.name] = get_model(n.impl).init(sub)
            else:
                init_fn, _ = self._component(n)
                params[n.name] = init_fn(sub, n.config)
            for c in n.children:
                key = walk(c, key)
            return key

        walk(self.root, key)
        return params

    @staticmethod
    def _component(n: Node) -> tuple[Callable, Callable]:
        reg = _KIND_REGISTRY[n.type]
        try:
            return reg[n.impl]
        except KeyError:
            raise KeyError(
                f"no {n.type} component {n.impl!r}; known: {sorted(reg)}"
            ) from None

    # -- compilation -------------------------------------------------------

    def build(self) -> Callable[..., jax.Array]:
        """Close the tree into one ``(params, x, compute_dtype=) -> (B,)``.

        Purely functional over the params dict, so it jits, grads, and
        shards like any model ``apply``.
        """
        import inspect

        def compile_node(n: Node) -> Callable[[dict, jax.Array, Any], jax.Array]:
            if n.type == "MODEL":
                spec = get_model(n.impl)
                takes_dtype = "compute_dtype" in inspect.signature(
                    spec.apply
                ).parameters

                def run_model(params, x, dtype, _spec=spec, _td=takes_dtype, _n=n):
                    p = params[_n.name]
                    return _spec.apply(p, x, compute_dtype=dtype) if _td else _spec.apply(p, x)

                return run_model
            _, apply_fn = self._component(n)
            kids = tuple(compile_node(c) for c in n.children)
            if n.type == "TRANSFORMER":
                return lambda params, x, dtype, _a=apply_fn, _k=kids[0], _n=n: _k(
                    params, _a(params[_n.name], x, _n.config), dtype
                )
            if n.type == "OUTPUT_TRANSFORMER":
                return lambda params, x, dtype, _a=apply_fn, _k=kids[0], _n=n: _a(
                    params[_n.name], _k(params, x, dtype), _n.config
                )
            if n.type == "COMBINER":
                return lambda params, x, dtype, _a=apply_fn, _ks=kids, _n=n: _a(
                    params[_n.name], [k(params, x, dtype) for k in _ks], _n.config
                )
            # ROUTER: every branch scores the full batch; the router's per-row
            # simplex weights select/blend — static shapes, one executable.
            def run_router(params, x, dtype, _a=apply_fn, _ks=kids, _n=n):
                w = _a(params[_n.name], x, _n.config)
                ys = jnp.stack([k(params, x, dtype) for k in _ks])
                return jnp.einsum("bn,nb->b", w.astype(jnp.float32), ys)

            return run_router

        root_fn = compile_node(self.root)

        def apply(params, x, compute_dtype=jnp.float32):
            return root_fn(params, x, compute_dtype)

        return apply

    # -- registry integration ---------------------------------------------

    def as_model_spec(self, register: bool = True) -> ModelSpec:
        """Expose the compiled graph as a registry model, making an ensemble
        a drop-in ``CCFD_MODEL`` for Scorer/server/CLI."""
        graph_apply = self.build()
        jitted = jax.jit(graph_apply, static_argnames=("compute_dtype",))

        def logits(params, x, compute_dtype=jnp.float32):
            return _logit(graph_apply(params, x, compute_dtype=compute_dtype))

        spec = ModelSpec(
            name=self.name,
            init=self.init,
            apply=jitted,
            logits=logits,
            trainable=False,  # node set may include non-differentiable trees
        )
        if register:
            # Never clobber a built-in model: a CR named "mlp"/"modelfull"
            # would silently swap graph-shaped params under every later
            # Scorer(model_name=...). Re-registering a graph name is fine
            # (reloading a CR is the common case).
            try:
                existing = get_model(self.name)
            except KeyError:
                existing = None
            if existing is not None and self.name not in _GRAPH_NAMES:
                raise ValueError(
                    f"graph name {self.name!r} collides with a registered "
                    f"model; set metadata.name in the CR to a unique name"
                )
            _GRAPH_NAMES.add(self.name)
            register_model(spec)
        return spec


def load_graph_cr(path: str, register: bool = True) -> ModelSpec:
    """CR file -> registered ModelSpec (what ``CCFD_GRAPH_CR`` points at)."""
    g = InferenceGraph.from_cr_file(path)
    return g.as_model_spec(register=register)
