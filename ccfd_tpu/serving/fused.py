"""FusedDecisionScorer: the single-dispatch decision plane.

Wraps the row-family :class:`~ccfd_tpu.serving.scorer.Scorer` with the
compiled decision program (ops/fused_decision.py): one jitted XLA
executable per batch bucket takes the staged feature rows and returns
routed verdicts — the model probability, the FRAUD_THRESHOLD comparison
and the first-matching rule index, all evaluated on device and shipped
back as ONE packed (B, 2) float32 transfer. The only host work left on
the path is transport, the batcher, and the route seam's bookkeeping;
``Router._route_inner`` consumes the fired indices without re-deriving
anything (router/router.py ``decision_fn``).

Contracts kept truthful:

- **Parity is bit-exact.** The decision program traces the SAME forward
  the staged path dispatches — the Pallas fused kernel when the base
  scorer serves it (with the identical wire-dtype cast, now inside the
  jit), the XLA graph otherwise — and the rules tensor pre-casts bounds
  exactly like ``Condition.mask``. Pinned by tests/test_fused_decision.py.
- **Non-vectorizable rules refuse fusion loudly.** A rule base carrying a
  custom ``when_fn`` fails :func:`~ccfd_tpu.ops.fused_decision.compile_rules`
  at construction: ONE warning, ``enabled`` False, the whole set serves
  staged. Never a silent per-row fallback.
- **The ladder still rules.** An unhealthy fused executable (dispatch
  failure, lowering error) disables the plane — latched for
  lowering-class failures, until the next successful swap precompile
  otherwise — and the call falls back to the STAGED path
  (``Scorer.score`` + host rules); if the device itself is sick that
  raises through to the router's host and rules tiers unchanged.
- **Swaps precompile before publishing.** The plane registers a
  prepublish hook on the base scorer: ``swap_params`` runs every bucket
  of the fused grid against the staged artifacts (under the
  ``fused.warm`` compile stage) BEFORE the reference flip, exactly like
  the seq variant swap — a promotion never pays serving-stage compiles.

The per-bucket executable grid reports through ``executable_grid()``
(device-telemetry inventory entry ``fused_decision``) with dispatch
counters, mirroring the PR 8/PR 10 machinery it generalizes.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any

import jax
import numpy as np

from ccfd_tpu.ops.fused_decision import (
    UnvectorizableRuleSet,
    build_decision_fn,
    compile_rules,
)
from ccfd_tpu.router.rules import RuleSet
from ccfd_tpu.runtime.faults import device_seam

log = logging.getLogger(__name__)


class FusedDecisionScorer:
    """One-dispatch scorer+router verdict plane over a row Scorer.

    ``decide(x) -> (proba, fired)``: float32 probabilities bit-identical
    to the staged path, int64 fired-rule indices into ``rules.rules``
    (the router's own ordering), or ``(proba, None)`` when the plane fell
    back to the staged path (the router then evaluates rules on host —
    staged semantics, not a third behavior).
    """

    def __init__(
        self,
        scorer: Any,
        rules: RuleSet,
        *,
        registry: Any = None,
        profiler: Any = None,
        strict: bool = False,
    ):
        self._base = scorer
        self.rules = rules
        self._profiler = profiler
        self._lock = threading.Lock()
        self._dispatch_counts: dict[int, int] = {}
        self._disabled = False
        self._latched = False
        self.enabled = False
        self.host_syncs = 0  # device->host materializations (the transfer)
        self.staged_fallbacks = 0
        self._plan = None
        self._decide_xla = None
        self._decide_fused = None
        self._decide_preq = None
        c = (registry.counter if registry is not None else None)
        self._c_decide = c and c("fused_decision_dispatches_total",
                                 "fused decision-kernel dispatches")
        self._c_fallback = c and c(
            "fused_decision_fallbacks_total",
            "decide() calls served by the staged path because the fused "
            "executable was unhealthy or never compiled")
        reason = None
        if getattr(scorer, "mesh", None) is not None:
            reason = ("mesh-sharded scorer: the decision program has no "
                      "shard_map composition yet")
        elif getattr(scorer, "_apply", None) is None:
            reason = f"scorer {type(scorer).__name__} has no traceable apply"
        if reason is None:
            try:
                self._plan = compile_rules(rules)
            except UnvectorizableRuleSet as e:
                reason = str(e)
        if reason is not None:
            # ONE loud compile-time decision for the whole rule set /
            # scorer pairing; per-row or per-batch surprises are banned
            if strict:
                raise RuntimeError(f"fused decision refused: {reason}")
            log.warning(
                "fused decision disabled; serving the STAGED path: %s",
                reason)
            return
        self.enabled = True

    # -- decision-program construction --------------------------------------

    def _fn_for(self, fused_params: Any):
        """The jitted decision program matching the base scorer's live
        forward: the Pallas fused kernel when armed (identical wire-dtype
        cast, traced inside the jit), else the XLA apply. Built once per
        kind; jit caches one executable per bucket shape."""
        base = self._base
        if fused_params is not None:
            if self._decide_fused is None:
                mod = base._fused_mod
                wire = base._fused_in_dtype
                interpret = base._fused_interpret

                def forward(fp, x):
                    # the SAME cast the staged wire applies host-side
                    # (round-to-nearest-even either way: bit-identical)
                    xw = x.astype(wire) if x.dtype != wire else x
                    return mod.fused_score(
                        fp, xw, tile=mod.fit_tile(x.shape[0]),
                        interpret=interpret)

                self._decide_fused = build_decision_fn(forward, self._plan)
            return self._decide_fused
        if self._decide_xla is None:
            self._decide_xla = build_decision_fn(base._apply, self._plan)
        return self._decide_xla

    def _fn_preq(self):
        """Decision program for the q8 int8 WIRE: the staged path ships
        host-prequantized (q, s) rows (Scorer._fused_dispatch), and
        bit-exact parity means the fused program must consume the SAME
        wire — the full-kernel device requantization differs in the last
        float32 ulp. Rows ship as a third f32 arg only when the rule plan
        reads feature columns; otherwise the einsum's feature lanes are
        all-zero selectors and a device-side zeros placeholder costs no
        transfer."""
        if self._decide_preq is None:
            import jax.numpy as jnp

            from ccfd_tpu.ops.fused_decision import eval_plan

            base = self._base
            mod = base._fused_mod
            interpret = base._fused_interpret
            plan = self._plan
            n_feat = plan.sel.shape[2] - 1

            @jax.jit
            def decide(fp, q, s, x=None):
                proba = mod.fused_mlp_q8_score_preq(
                    fp, q, s, tile=mod.fit_tile(q.shape[0]),
                    interpret=interpret,
                ).astype(jnp.float32)
                if x is None:
                    x = jnp.zeros((q.shape[0], n_feat), jnp.float32)
                fired = eval_plan(plan, x, proba)
                return jnp.stack([proba, fired.astype(jnp.float32)], axis=1)

            self._decide_preq = decide
        return self._decide_preq

    def _snapshot(self) -> tuple[Any, Any, Any]:
        with self._base._lock:
            return (self._base._params, self._base._fused_params,
                    self._base._preq_norm)

    def _preq_live(self, fused_params: Any, preq_norm: Any) -> bool:
        base = self._base
        return (fused_params is not None and preq_norm is not None
                and getattr(base, "_preq_wire", False)
                and base.mesh is None)

    # -- serving -------------------------------------------------------------

    def decide(self, x: np.ndarray) -> tuple[np.ndarray, np.ndarray | None]:
        """(n, F) rows -> (proba, fired) through the fused grid, or the
        staged fallback ``(proba, None)`` when the plane is unhealthy."""
        x = np.asarray(x, np.float32)
        n = x.shape[0]
        if n == 0:
            return np.zeros((0,), np.float32), np.zeros((0,), np.int64)
        if not self.enabled or self._disabled:
            return self._staged(x)
        params, fused_params, preq_norm = self._snapshot()
        preq = self._preq_live(fused_params, preq_norm)
        fn = self._fn_preq() if preq else self._fn_for(fused_params)
        which = params if fused_params is None else fused_params
        base = self._base
        largest = base.batch_sizes[-1]
        t0 = time.perf_counter()
        pending: list[tuple[jax.Array, int]] = []
        chunks: list[np.ndarray] = []
        start = 0
        try:
            while start < n:
                take = min(n - start, largest)
                b = base.bucket(take)
                chunk = x[start:start + take]
                if take < b:
                    chunk = np.concatenate(
                        [chunk, np.zeros((b - take, x.shape[1]), np.float32)]
                    )
                # same fault seam as the staged dispatch: an injected
                # device_hang / compile_stall rides the fused path too
                device_seam("dispatch")
                with self._lock:
                    self._dispatch_counts[b] = (
                        self._dispatch_counts.get(b, 0) + 1)
                out = self._dispatch_one(fn, which, chunk, preq, preq_norm)
                pending.append((out, take))
                if len(pending) >= 2:
                    done, took = pending.pop(0)
                    chunks.append(np.asarray(done)[:took])
                    self.host_syncs += 1
                start += take
            for done, took in pending:
                # the single allowed sync: ONE packed (b, 2) transfer
                # carries score + threshold verdict + fired rule together
                chunks.append(np.asarray(done)[:took])
                self.host_syncs += 1
        # ccfd-lint: disable=counted-drops -- _disable logs the failure with its latch decision and _staged counts it in fused_decision_fallbacks_total
        except Exception as e:  # noqa: BLE001 - unhealthy executable:
            # disable the plane (latched for lowering-class failures) and
            # serve THIS call staged; a sick device raises out of the
            # staged path into the router's host/rules tiers
            self._disable(e)
            return self._staged(x)
        if self._c_decide:
            self._c_decide.inc(n)
        if self._profiler is not None:
            self._profiler.observe(
                "fused.decide", dispatch_s=time.perf_counter() - t0,
                batch=n, rows=n)
        packed = chunks[0] if len(chunks) == 1 else np.concatenate(chunks)
        proba = np.ascontiguousarray(packed[:, 0], np.float32)
        # rule indices are small ints: exact in the float32 lane
        fired = packed[:, 1].astype(np.int64)
        return proba, fired

    def _dispatch_one(self, fn: Any, which: Any, chunk: np.ndarray,
                      preq: bool, preq_norm: Any) -> jax.Array:
        """One bucket-padded chunk through the decision program. In preq
        mode the chunk ships on the SAME int8 wire the staged q8 path
        uses (host prequantization, byte-counted puts); rows ride along
        in f32 only when the rule plan reads feature columns."""
        base = self._base
        if not preq:
            return fn(which, base._put_batch(chunk))
        q, s = base._fused_mod.prequantize_rows_numpy(preq_norm, chunk)
        if base.telemetry is None:
            import jax.numpy as jnp

            qd, sd = jnp.asarray(q), jnp.asarray(s)
        else:
            import jax.numpy as jnp

            from ccfd_tpu.observability.device import timed_put

            qd = timed_put(base.telemetry, q.nbytes, lambda: jnp.asarray(q))
            sd = timed_put(base.telemetry, s.nbytes, lambda: jnp.asarray(s))
        if self._plan.needs_features:
            return fn(which, qd, sd, base._put_batch(chunk))
        return fn(which, qd, sd)

    def _staged(self, x: np.ndarray) -> tuple[np.ndarray, None]:
        """Whole-call staged fallback: base scorer + host rules (the
        router evaluates them on the returned ``fired=None``). One
        semantics per batch, never a per-row split."""
        self.staged_fallbacks += 1
        if self._c_fallback:
            self._c_fallback.inc(len(x))
        return np.asarray(self._base.score(x), np.float32), None

    def _disable(self, e: Exception) -> None:
        latch = self._base._is_lowering_error(e)
        log.warning(
            "fused decision executable failed (%r); serving the staged "
            "path %s", e,
            "permanently" if latch else "until the next swap precompile")
        self._disabled = True
        self._latched = self._latched or latch

    # -- warmup / swap precompile -------------------------------------------

    def warmup(self) -> None:
        """Precompile the whole fused decision grid (every batch bucket)
        under the ``fused.warm`` compile stage — serving dispatches then
        run with zero serving-stage compiles."""
        if not self.enabled:
            return
        self._precompile(*self._snapshot())

    def prepublish(self, staged: Any, staged_fused: Any,
                   staged_preq_norm: Any, staged_host: Any) -> None:
        """Scorer prepublish hook: run the staged artifacts through every
        bucket of the decision grid BEFORE ``swap_params`` flips the
        serving reference — the seq variant swap's discipline applied to
        the fused grid. A healthy precompile re-arms a transiently
        disabled plane; a latched (lowering) disable stays latched."""
        if not self.enabled:
            return
        self._precompile(staged, staged_fused, staged_preq_norm)

    def _precompile(self, params: Any, fused_params: Any,
                    preq_norm: Any) -> None:
        from ccfd_tpu.observability.profile import compile_stage

        preq = self._preq_live(fused_params, preq_norm)
        fn = self._fn_preq() if preq else self._fn_for(fused_params)
        which = params if fused_params is None else fused_params
        base = self._base
        try:
            with compile_stage("fused.warm"):
                for b in base.batch_sizes:
                    zeros = np.zeros((b, base.num_features), np.float32)
                    jax.block_until_ready(
                        self._dispatch_one(fn, which, zeros, preq,
                                           preq_norm))
        # ccfd-lint: disable=counted-drops -- _disable logs the failure with its latch decision; later decide() calls count staged service in fused_decision_fallbacks_total
        except Exception as e:  # noqa: BLE001 - a grid that cannot compile
            # must not brick warmup or a swap publish: the plane disables
            # and serving continues staged
            self._disable(e)
            return
        if not self._latched:
            self._disabled = False

    # -- observability -------------------------------------------------------

    def executable_grid(self) -> dict:
        """The fused decision grid's executable-inventory entry
        (device-telemetry source ``fused_decision``), mirroring the row
        and seq families: bucket ladder, per-bucket dispatch counts, and
        the plane's health so a scrape shows WHAT is serving verdicts."""
        with self._lock:
            counts = dict(self._dispatch_counts)
        _, fused_params, preq_norm = (self._snapshot() if self.enabled
                                      else (None, None, None))
        forward = "xla"
        if fused_params is not None:
            forward = ("fused_kernel_int8_wire"
                       if self._preq_live(fused_params, preq_norm)
                       else "fused_kernel")
        return {
            "model": getattr(self._base.spec, "name", "?"),
            "batch_sizes": list(self._base.batch_sizes),
            "forward": forward,
            "rules": (self._plan.n_rules if self._plan is not None else 0),
            "needs_features": bool(self._plan is not None
                                   and self._plan.needs_features),
            "enabled": bool(self.enabled and not self._disabled),
            "staged_fallbacks": int(self.staged_fallbacks),
            "host_syncs": int(self.host_syncs),
            "dispatches": {str(b): int(c)
                           for b, c in sorted(counts.items())},
        }


__all__ = ["FusedDecisionScorer"]
