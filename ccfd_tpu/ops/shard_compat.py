"""Version-compat shim for ``shard_map`` and the vma helpers around it.

jax moved ``shard_map`` from ``jax.experimental.shard_map`` to the
top-level ``jax.shard_map`` (renaming the replication check from
``check_rep`` to ``check_vma``), and grew ``jax.lax.pcast`` for marking
scan carries varying-across-mesh — neither exists on the older runtimes
this repo also targets (the container pins jax 0.4.x, where only the
experimental module is real). Every ``shard_map`` call site in the ops
and serving layers goes through this shim so one jax upgrade/downgrade
never reintroduces the tier-1 ``AttributeError: module 'jax' has no
attribute 'shard_map'`` that blocked the ring/ulysses sequence losses
(ROADMAP item 2).
"""

from __future__ import annotations

from typing import Any

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` where it exists, else the experimental spelling.

    ``check_vma`` maps onto the old API's ``check_rep``; when the caller
    relies on the new varying-across-mesh annotations (``pcast``, absent
    on old jax — see :func:`pcast_varying`'s identity fallback), the old
    replication checker cannot see them, so the fallback always disables
    the check rather than mis-asserting replication the body never
    promised.
    """
    top = getattr(jax, "shard_map", None)
    if top is not None:
        return top(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def pcast_varying(x: Any, axis_name: str) -> Any:
    """``jax.lax.pcast(x, (axis_name,), to="varying")`` on jax versions
    that have the vma system; identity otherwise (pre-vma shard_map has
    no varying annotation for a scan carry to need)."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, (axis_name,), to="varying")
