"""Ring attention: exact attention over sequence-sharded inputs.

The reference has no sequence dimension (SURVEY.md §5 "long-context: N/A"),
but this framework treats long-context as first-class: the sequence scorer
(ccfd_tpu/models/seq.py) attends over per-customer transaction histories,
and histories longer than one chip's memory shard over the mesh. Ring
attention computes *exact* softmax attention with the sequence dimension
sharded: each device keeps its Q shard resident and rotates K/V shards
around the ring with ``lax.ppermute`` (ICI neighbor hops, no all-gather),
accumulating the softmax online (flash-attention style running max /
denominator), so peak memory per device is O(L_local) regardless of total
sequence length.

Implemented with ``shard_map`` over a named mesh axis; the per-device body
is a ``lax.scan`` of (blockwise attention + ppermute), fully compiled — no
host round-trips per ring step.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ccfd_tpu.ops.shard_compat import pcast_varying, shard_map


def _online_block(q, k_blk, v_blk, m, l, o):
    """One blockwise-attention accumulation step (numerically stable).

    q: (B, H, Lq, D); k_blk/v_blk: (B, H, Lk, D);
    m: (B, H, Lq) running max; l: (B, H, Lq) running denom;
    o: (B, H, Lq, D) running numerator.
    """
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk, preferred_element_type=jnp.float32)
    s = s * scale.astype(jnp.float32)
    m_new = jnp.maximum(m, s.max(axis=-1))
    correction = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * correction + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v_blk.dtype), v_blk,
                    preferred_element_type=jnp.float32)
    o_new = o * correction[..., None] + pv
    return m_new, l_new, o_new


def reference_attention(q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
    """Plain full attention (B, H, L, D) — the single-device reference."""
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(v.dtype)


def _ring_body(q, k, v, axis_name: str):
    """Per-device program: accumulate over all ring positions."""
    n = jax.lax.psum(1, axis_name)
    batch, heads, lq, d = q.shape
    m0 = jnp.full((batch, heads, lq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((batch, heads, lq), jnp.float32)
    o0 = jnp.zeros((batch, heads, lq, d), jnp.float32)
    # the accumulators become device-varying after one step; mark the scan
    # carry as varying over the ring axis up front (shard_map scan-vma rule;
    # identity on pre-vma jax, ops/shard_compat.py)
    m0, l0, o0 = (pcast_varying(t, axis_name) for t in (m0, l0, o0))
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, _):
        k_blk, v_blk, m, l, o = carry
        m, l, o = _online_block(q, k_blk, v_blk, m, l, o)
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, m, l, o), None

    # n-1 (accumulate + rotate) steps, then a final accumulate with no
    # rotation — the last permute's output would never be consumed and each
    # skipped ppermute saves ICI traffic in forward AND backward.
    (k, v, m, l, o), _ = jax.lax.scan(step, (k, v, m0, l0, o0), None, length=n - 1)
    m, l, o = _online_block(q, k, v, m, l, o)
    return (o / l[..., None]).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str,
) -> jax.Array:
    """Exact attention with L sharded over ``axis_name``. (B, H, L, D) in/out.

    L must divide evenly by the axis size. Non-causal (transaction histories
    attend bidirectionally).
    """
    spec = P(None, None, axis_name, None)
    fn = shard_map(
        partial(_ring_body, axis_name=axis_name),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
