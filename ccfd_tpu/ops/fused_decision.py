"""Fused decision program: score + threshold + rules in ONE executable.

The serving hot path used to be staged: device dispatch produces (B,)
probabilities, the host materializes them, then ``RuleSet.evaluate``
re-walks the batch rule by rule in numpy before the router can group
process starts. PRETZEL's white-box result (PAPERS.md) is that the wins
live in collapsing the pipeline's operator graph into one executable —
so this module compiles the *rule base itself* into tensors and builds a
jitted program that takes the staged feature batch and returns routed
verdicts: ``(proba, fired_rule_index)`` packed as one (B, 2) float32
array, i.e. exactly ONE device->host transfer per dispatch and zero host
compute between score and route.

Compilation: every vectorizable :class:`~ccfd_tpu.router.rules.Condition`
(``>/>=/</<=/==/!=/between`` over the 30 features or ``proba``) becomes
one slot of a stacked predicate tensor — an operand column index
``idx (R, C)``, an op code ``op (R, C)`` and bounds ``lo/hi (R, C)``.
Inside the jit the batch evaluates as one gather
(``vals = take([x | proba], idx, axis=1)``), an op-coded compare, an
AND-reduce over each rule's conjunction and an argmax over the
salience-ordered match matrix — bit-for-bit ``RuleSet.evaluate``
first-match semantics, because:

- rules stay in ``RuleSet.rules`` order (already salience-sorted, stable)
  and ``argmax`` over booleans returns the FIRST max index;
- every bound is pre-cast with ``np.float32`` — the same
  ``col.dtype.type(value)`` cast ``Condition.mask`` applies (x and proba
  are float32 columns on both paths);
- the gather moves values verbatim, no arithmetic touches them.

Non-vectorizable rules (a custom ``when_fn`` callable) CANNOT compile:
:func:`compile_rules` raises :class:`UnvectorizableRuleSet` so the caller
forces the staged path for the WHOLE rule set with one loud warning —
never a silent per-row fallback that would split a batch across two
semantics (see serving/fused.py).

The model forward composes into the same jit: the Pallas fused kernels
(ops/fused_mlp.py, ops/fused_mlp_q8.py) or the model's XLA graph — the
builder takes the forward as a traceable callable, so whatever the
serving Scorer dispatches is what fuses here.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ccfd_tpu.data.ccfd import FEATURE_NAMES
from ccfd_tpu.router.rules import PROBA_FIELD, RuleSet

# op codes for the stacked predicate tensor; OP_TRUE pads rules with
# fewer conditions than the widest one (and the default rule's empty
# conjunction) so the AND-reduce is rectangular
OP_GT, OP_GE, OP_LT, OP_LE, OP_EQ, OP_NE, OP_BETWEEN, OP_TRUE = range(8)
_OP_CODES = {">": OP_GT, ">=": OP_GE, "<": OP_LT, "<=": OP_LE,
             "==": OP_EQ, "!=": OP_NE, "between": OP_BETWEEN}


class UnvectorizableRuleSet(ValueError):
    """The rule base contains a predicate that cannot compile to the
    stacked tensor form (a custom ``when_fn`` callable). The whole set
    must serve staged — semantics may not split within a batch."""


@dataclass(frozen=True)
class RulePlan:
    """A RuleSet compiled to stacked predicate tensors.

    ``sel``  (R, C, F+1) float32 one-hot column selector (slot F = proba)
    ``idx``  (R, C) int32 operand column index (= argmax of ``sel``; the
    dense gather form — evaluating through ``sel`` would pay an F-wide
    einsum per condition for the same exact value)
    ``op``   (R, C) int32 op codes (OP_TRUE = padding / empty conjunction)
    ``lo``   (R, C) float32 lower/scalar bound, pre-cast like the host path
    ``hi``   (R, C) float32 upper bound (``between`` only; else == lo)
    ``processes`` / ``names``: per-rule RHS bookkeeping for the route seam
    ``needs_features``: any condition reads a feature column — the
    decision dispatch must then ship float32 rows (a reduced-precision
    wire would round the very values the predicates compare)
    """

    sel: np.ndarray
    idx: np.ndarray
    op: np.ndarray
    lo: np.ndarray
    hi: np.ndarray
    processes: tuple[str, ...]
    names: tuple[str, ...]
    needs_features: bool
    rules: Any  # the source RuleSet: identity-checked at the route seam

    @property
    def n_rules(self) -> int:
        return self.sel.shape[0]


def compile_rules(rules: RuleSet,
                  feature_names: Sequence[str] = FEATURE_NAMES) -> RulePlan:
    """RuleSet -> RulePlan, or raise :class:`UnvectorizableRuleSet`.

    Raising (instead of returning a partial plan) is the satellite-3
    contract: one non-vectorizable rule forces the STAGED path for the
    whole set, decided loudly at compile time — a per-row fallback would
    evaluate half a batch under tensor semantics and half under host
    semantics, and any drift between them would split routing decisions
    within one micro-batch.
    """
    n_feat = len(feature_names)
    for r in rules.rules:
        if getattr(r, "when_fn", None) is not None:
            raise UnvectorizableRuleSet(
                f"rule {r.name!r} carries a custom when_fn callable; "
                f"callables cannot compile to the stacked predicate "
                f"tensor — the whole rule set serves staged"
            )
    n_rules = len(rules.rules)
    width = max(1, max(len(r.when) for r in rules.rules))
    sel = np.zeros((n_rules, width, n_feat + 1), np.float32)
    idx = np.zeros((n_rules, width), np.int32)  # padding gathers col 0;
    op = np.full((n_rules, width), OP_TRUE, np.int32)  # OP_TRUE masks it
    lo = np.zeros((n_rules, width), np.float32)
    hi = np.zeros((n_rules, width), np.float32)
    needs_features = False
    for i, rule in enumerate(rules.rules):
        for j, cond in enumerate(rule.when):
            if cond.fld == PROBA_FIELD:
                col = n_feat
            else:
                col = feature_names.index(cond.fld)
                needs_features = True
            sel[i, j, col] = 1.0
            idx[i, j] = col
            op[i, j] = _OP_CODES[cond.op]
            # the SAME cast Condition.mask applies (col.dtype.type(value)
            # on float32 columns): ==/!= against a non-dyadic literal must
            # hit or miss identically on both paths
            if cond.op == "between":
                lo[i, j] = np.float32(cond.value[0])
                hi[i, j] = np.float32(cond.value[1])
            else:
                lo[i, j] = np.float32(cond.value)
                hi[i, j] = lo[i, j]
    return RulePlan(sel=sel, idx=idx, op=op, lo=lo, hi=hi,
                    processes=tuple(r.process for r in rules.rules),
                    names=tuple(r.name for r in rules.rules),
                    needs_features=needs_features, rules=rules)


def eval_plan(plan: RulePlan, x: jax.Array, proba: jax.Array) -> jax.Array:
    """(B, F) float32 rows + (B,) float32 proba -> (B,) int32 fired index.

    Traceable; runs inside the decision jit. One gather pulls every
    condition's operand column (``plan.idx`` — exact, and R*C elements
    per row instead of the one-hot einsum's R*C*F multiply-adds; proba
    slots broadcast in via ``where`` rather than concatenating proba
    onto x, which would copy the whole feature block per dispatch), one
    op-coded compare builds the (B, R, C) predicate tensor, the
    AND-reduce collapses conjunctions, and argmax over the
    salience-ordered (B, R) match matrix IS first-match-wins (argmax
    returns the first True). A default rule (empty ``when`` -> all
    OP_TRUE) guarantees every row matches something, exactly like
    ``RuleSet.evaluate``.
    """
    xf = x.astype(jnp.float32)
    pf = proba.astype(jnp.float32)
    n_feat = xf.shape[1]
    idx = jnp.asarray(plan.idx)  # (R, C); slot n_feat = proba
    feat = jnp.take(xf, jnp.clip(idx, 0, n_feat - 1), axis=1)  # (B, R, C)
    vals = jnp.where(idx[None, :, :] == n_feat, pf[:, None, None], feat)
    op = jnp.asarray(plan.op)[None, :, :]  # (1, R, C)
    lo = jnp.asarray(plan.lo)[None, :, :]
    hi = jnp.asarray(plan.hi)[None, :, :]
    pred = jnp.select(
        [op == OP_GT, op == OP_GE, op == OP_LT, op == OP_LE,
         op == OP_EQ, op == OP_NE, op == OP_BETWEEN],
        [vals > lo, vals >= lo, vals < lo, vals <= lo,
         vals == lo, vals != lo, (vals >= lo) & (vals <= hi)],
        default=jnp.ones_like(vals, bool),  # OP_TRUE padding
    )
    matches = pred.all(axis=2)  # (B, R)
    return jnp.argmax(matches, axis=1).astype(jnp.int32)


def build_decision_fn(forward: Callable[[Any, jax.Array], jax.Array],
                      plan: RulePlan) -> Callable[[Any, jax.Array], jax.Array]:
    """One jitted program: staged rows -> packed routed verdicts.

    ``forward(params, x)`` is whatever the serving path dispatches — the
    Pallas fused kernel, the XLA graph, the q8 readout — traced INTO the
    same executable as the rules evaluation. Returns (B, 2) float32:
    column 0 the probability (identical bits to the staged forward),
    column 1 the fired rule index (small ints are exact in float32; one
    packed array = one D2H transfer carrying the whole verdict).

    jit caches one executable per batch bucket shape — the (L, B) grid
    generalization of the scorer's bucket ladder; warmup precompiles it
    under the ``fused.warm`` compile stage (serving/fused.py).
    """

    @jax.jit
    def decide(params: Any, x: jax.Array) -> jax.Array:
        proba = forward(params, x).astype(jnp.float32)
        fired = eval_plan(plan, x, proba)
        return jnp.stack([proba, fired.astype(jnp.float32)], axis=1)

    return decide


__all__ = [
    "RulePlan", "UnvectorizableRuleSet", "compile_rules", "eval_plan",
    "build_decision_fn",
]
