"""Int8 quantized sequence scorer: the seq transformer's serving variant.

The long-context sibling of :mod:`ccfd_tpu.ops.quant` (the ``mlp_q8``
graph), with the SAME quantization conventions so the zoo's two quantized
members share one accuracy story:

- **Weights**: symmetric per-output-channel int8 at quantization time
  (``quantize_seq``): scale_o = max|W[:, o]| / 127, for every dense weight
  in the transformer (embed, per-block qkv/proj/mlp_in/mlp_out, head).
- **Activations**: symmetric per-row dynamic int8 at run time — for the
  (B, L, D) streams each of the B*L token rows quantizes independently,
  exactly the per-row rule ``quant._quantize_rows`` applies to (B, F).
- **Accumulation**: int32 via ``preferred_element_type``; dequant + bias
  stay f32. Layer norms, softmax attention, GELU and the sinusoidal
  positions run in the compute dtype (bf16/f32) — they are O(L*D) against
  the matmuls' O(L*D^2) and carry the numerics the int8 grid would wreck.

On a TPU the MXU runs int8 x int8 -> int32 at up to twice the bf16 rate
and the weights ship/reside at a quarter of f32 — the same hardware
argument as ``mlp_q8``, here applied to the dispatch-bound seq path
(BENCH_r05: 1412 ms dispatch vs 13 ms assembly). As with ``mlp_q8`` the
claim made on CPU captures is accuracy preservation, not speed.

Registered in the model zoo as ``seq_q8``; it reaches serving ONLY through
the lifecycle shadow lane (AUC/PSI guardrails against the bf16 champion —
tests/test_seq_lifecycle.py exercises both the promote and the reject
path), never by a blind swap.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from ccfd_tpu.models.seq import N_HEADS, _layer_norm, _positions
from ccfd_tpu.ops.ring_attention import reference_attention

Params = Mapping[str, Any]

_EPS = 1e-8


def _q_weight(w: Any) -> dict[str, jax.Array]:
    """(in, out) f32 weight -> {"wq" int8, "scale" f32 (out,)} — the
    per-output-channel rule of :func:`ccfd_tpu.ops.quant.quantize_mlp`."""
    w = np.asarray(w, np.float32)
    scale = np.maximum(np.abs(w).max(axis=0) / 127.0, _EPS)
    wq = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return {"wq": jnp.asarray(wq), "scale": jnp.asarray(scale, jnp.float32)}


def _q_dense_params(layer: Mapping[str, Any]) -> dict[str, jax.Array]:
    out = _q_weight(layer["w"])
    out["b"] = jnp.asarray(np.asarray(layer["b"], np.float32))
    return out


def quantize_seq(params: Params) -> Params:
    """f32/bf16 seq params (models/seq.py layout) -> int8 inference params.

    Layer norms, biases and the normalizer stay f32; every dense weight
    becomes {"wq", "scale", "b"}."""
    f32 = lambda t: jax.tree.map(  # noqa: E731
        lambda a: jnp.asarray(np.asarray(a, np.float32)), dict(t))
    blocks = []
    for blk in params["blocks"]:
        blocks.append({
            "ln1": f32(blk["ln1"]),
            "qkv": _q_dense_params(blk["qkv"]),
            "proj": _q_dense_params(blk["proj"]),
            "ln2": f32(blk["ln2"]),
            "mlp_in": _q_dense_params(blk["mlp_in"]),
            "mlp_out": _q_dense_params(blk["mlp_out"]),
        })
    return {
        "norm": f32(params["norm"]),
        "embed": _q_dense_params(params["embed"]),
        "blocks": blocks,
        "head": {
            "ln": f32(params["head"]["ln"]),
            **_q_weight(params["head"]["w"]),
            "b": jnp.asarray(np.asarray(params["head"]["b"], np.float32)),
        },
    }


def is_quantized(params: Params) -> bool:
    """Structural sniff the serving layer keys variant dispatch on: a
    quantized seq tree carries int8 "wq" leaves where the bf16 tree has
    "w" (SeqScorer.swap_params re-binds its jitted apply off this)."""
    try:
        return "wq" in params["embed"] and "blocks" in params
    except (TypeError, KeyError):
        return False


def _rowquant_tokens(h: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-token symmetric int8: (..., D) -> ((..., D) int8, (..., 1) f32)."""
    amax = jnp.max(jnp.abs(h.astype(jnp.float32)), axis=-1, keepdims=True)
    s = jnp.maximum(amax / 127.0, _EPS)
    q = jnp.clip(jnp.rint(h.astype(jnp.float32) / s), -127, 127).astype(jnp.int8)
    return q, s


def _q_dense(h: jax.Array, layer: Mapping[str, Any],
             compute_dtype) -> jax.Array:
    """One quantized dense over the token axis: (..., D_in) -> (..., D_out),
    int8 x int8 -> int32 inside, f32 dequant + bias, cast to compute dtype."""
    q, s = _rowquant_tokens(h)
    acc = jax.lax.dot_general(
        q, layer["wq"], (((q.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    out = acc.astype(jnp.float32) * s * layer["scale"] + layer["b"]
    return out.astype(compute_dtype)


def logits(
    params: Params,
    x: jax.Array,
    compute_dtype=jnp.bfloat16,
    attention_fn: Callable[..., jax.Array] | None = None,
    n_heads: int = N_HEADS,
    pos_length: int | None = None,
) -> jax.Array:
    """(B, L, F) -> (B,) fraud logit; the seq.logits graph with every
    dense matmul int8-quantized. The last block computes readout-only,
    like :func:`ccfd_tpu.models.seq.logits_readout` (the serving shape —
    this variant exists for the serving path), and ``pos_length``
    right-anchors positional encodings the same way (short L-bucket
    windows keep the full-L path's token positions)."""
    attn = attention_fn or reference_attention
    mu = jax.lax.stop_gradient(params["norm"]["mu"])
    sigma = jax.lax.stop_gradient(params["norm"]["sigma"])
    h = ((x.astype(jnp.float32) - mu) / sigma)
    h = _q_dense(h, params["embed"], compute_dtype)
    batch, length, d_model = h.shape
    pos = _positions(pos_length or length, d_model)[-length:]
    h = h + pos.astype(compute_dtype)[None]
    head_dim = d_model // n_heads

    def heads(t, lq):
        return t.reshape(batch, lq, n_heads, head_dim).transpose(0, 2, 1, 3)

    blocks = params["blocks"]
    for blk in blocks[:-1]:
        z = _layer_norm(h, blk["ln1"]["scale"], blk["ln1"]["bias"])
        qkv = _q_dense(z, blk["qkv"], compute_dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        a = attn(heads(q, length), heads(k, length), heads(v, length))
        a = a.transpose(0, 2, 1, 3).reshape(batch, length, d_model)
        h = h + _q_dense(a, blk["proj"], compute_dtype)
        z = _layer_norm(h, blk["ln2"]["scale"], blk["ln2"]["bias"])
        m = _q_dense(z, blk["mlp_in"], compute_dtype)
        m = jax.nn.gelu(m.astype(jnp.float32)).astype(compute_dtype)
        h = h + _q_dense(m, blk["mlp_out"], compute_dtype)

    # last block: K/V full, q (and everything after the attention) for
    # the readout token only — per-token row quantization is independent
    # across tokens, so projecting q from z[:, -1:] with the sliced
    # weight columns is numerically identical to slicing a full qkv
    blk = blocks[-1]
    z = _layer_norm(h, blk["ln1"]["scale"], blk["ln1"]["bias"])
    w_qkv = blk["qkv"]
    kv = _q_dense(z, {"wq": w_qkv["wq"][:, d_model:],
                      "scale": w_qkv["scale"][d_model:],
                      "b": w_qkv["b"][d_model:]}, compute_dtype)
    k, v = jnp.split(kv, 2, axis=-1)
    q = _q_dense(z[:, -1:, :], {"wq": w_qkv["wq"][:, :d_model],
                                "scale": w_qkv["scale"][:d_model],
                                "b": w_qkv["b"][:d_model]}, compute_dtype)
    a = attn(heads(q, 1), heads(k, length), heads(v, length))
    a = a.transpose(0, 2, 1, 3).reshape(batch, 1, d_model)
    hl = h[:, -1:, :] + _q_dense(a, blk["proj"], compute_dtype)
    z = _layer_norm(hl, blk["ln2"]["scale"], blk["ln2"]["bias"])
    m = _q_dense(z, blk["mlp_in"], compute_dtype)
    m = jax.nn.gelu(m.astype(jnp.float32)).astype(compute_dtype)
    hl = hl + _q_dense(m, blk["mlp_out"], compute_dtype)

    last = _layer_norm(hl[:, 0, :], params["head"]["ln"]["scale"],
                       params["head"]["ln"]["bias"])
    q, s = _rowquant_tokens(last)
    acc = jax.lax.dot_general(q, params["head"]["wq"],
                              (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    z = acc.astype(jnp.float32) * s * params["head"]["scale"] + params["head"]["b"]
    return z.reshape(batch)


@partial(jax.jit, static_argnames=("compute_dtype", "pos_length"))
def apply(params: Params, x: jax.Array, compute_dtype=jnp.bfloat16,
          pos_length: int | None = None) -> jax.Array:
    """(B, L, F) -> (B,) proba_1, int8 matmuls on the MXU."""
    return jax.nn.sigmoid(
        logits(params, x, compute_dtype, pos_length=pos_length))


# serving entry point: logits are already readout-optimized
apply_serving = apply


def register() -> None:
    """Register the seq family in the model zoo: ``seq`` (the bf16/f32
    champion graph) and ``seq_q8`` (this variant) resolve by name wherever
    models do — mirrors quant.register()'s ``mlp_q8``. Neither is
    trainable (the online trainer's step is the MLP's) and neither has a
    host-tier numpy forward; both apply over (B, L, F) histories, so the
    ROW Scorer cannot serve them — :class:`ccfd_tpu.serving.history.
    SeqScorer` is their serving layer (the operator special-cases
    ``model: seq``/``seq_q8`` accordingly)."""
    from ccfd_tpu.models import seq as seq_mod
    from ccfd_tpu.models.registry import ModelSpec, register_model

    register_model(
        ModelSpec("seq", seq_mod.init, seq_mod.apply, seq_mod.logits,
                  trainable=False)
    )

    def init_q8(key=None, **kw):
        return quantize_seq(
            seq_mod.init(key if key is not None else jax.random.PRNGKey(0),
                         **kw))

    register_model(
        ModelSpec("seq_q8", init_q8, apply, logits, trainable=False)
    )
