"""Int8 quantized MLP serving for fraud-scorer accuracy at reduced precision.

Architectural rationale: TPU MXUs execute int8 x int8 -> int32 matmuls at
up to twice the bf16 rate, and int8 weights/activations halve the HBM and
H2D bytes again over bf16 — on a wire-bound attachment that is the larger
win. NOTE these are the hardware's numbers, not this model's: ``mlp_q8``
has no recorded on-TPU throughput yet (the bench's ``quant_int8`` section
is TPU-gated; accuracy IS measured — see below and BASELINE.md "Model
variants"). Until a capture lands, the claim this module makes is accuracy
preservation, not speed. This module quantizes the flagship MLP
(models/mlp.py) for inference:

- **Weights**: symmetric per-output-channel int8 at quantization time
  (``quantize_mlp``): scale_o = max|W[:, o]| / 127. Per-channel keeps the
  widest layer's dynamic range without per-group bookkeeping.
- **Activations**: symmetric per-row dynamic int8 at run time: one amax
  per row, computed fused into the surrounding elementwise ops by XLA.
  Dynamic beats static calibration here because transaction feature rows
  vary wildly in magnitude (Amount spans cents to thousands).
- **Accumulation**: int32 via ``preferred_element_type`` — exact; the only
  rounding is the two quantizations. Dequant + bias + relu stay f32.

The int8 graph registers as model ``mlp_q8`` so the whole serving stack
(Scorer bucketing/warmup/swap, REST server, router) picks it up by name;
``apply_numpy`` implements the SAME quantized math for the host tier —
host and device disagree only in float rounding, not quantization.

Accuracy contract (asserted in tests/test_quant.py): AUC within 2e-3 of
the f32 forward and probabilities within ~0.03 — fraud routing decides
against FRAUD_THRESHOLD=0.5 (reference deploy/router.yaml:69-70), far
coarser than int8 noise.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np

Params = Mapping[str, Any]

_EPS = 1e-8
# finite ceiling for the host-tier per-layer activations: far above any
# healthy model's range, far below float32 overflow (see apply_numpy)
_H_CLAMP = 1e30


def quantize_mlp(params: Params) -> Params:
    """f32 MLP params (models/mlp.py layout) -> int8 inference params.

    Returns ``{"norm": {...f32...}, "layers": [{"wq": int8 (in, out),
    "scale": f32 (out,), "b": f32 (out,)}, ...]}``.
    """
    out_layers = []
    for layer in params["layers"]:
        w = np.asarray(layer["w"], np.float32)
        scale = np.abs(w).max(axis=0) / 127.0
        scale = np.maximum(scale, _EPS)
        wq = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
        out_layers.append({
            "wq": jnp.asarray(wq),
            "scale": jnp.asarray(scale, jnp.float32),
            "b": jnp.asarray(np.asarray(layer["b"], np.float32)),
        })
    return {
        "norm": {
            "mu": jnp.asarray(np.asarray(params["norm"]["mu"], np.float32)),
            "sigma": jnp.asarray(np.asarray(params["norm"]["sigma"], np.float32)),
        },
        "layers": out_layers,
    }


def _quantize_rows(h: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-row symmetric int8: (B, F) f32 -> ((B, F) int8, (B,) f32 scale)."""
    amax = jnp.max(jnp.abs(h), axis=1)
    s = jnp.maximum(amax / 127.0, _EPS)
    q = jnp.clip(jnp.rint(h / s[:, None]), -127, 127).astype(jnp.int8)
    return q, s


def _q_dense(h: jax.Array, layer: Mapping[str, Any]) -> jax.Array:
    """One quantized dense layer: f32 in, f32 out, int8 MXU matmul inside."""
    q, s_x = _quantize_rows(h)
    acc = jax.lax.dot_general(
        q, layer["wq"], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return acc.astype(jnp.float32) * s_x[:, None] * layer["scale"][None, :] + layer["b"]


def logits(params: Params, x: jax.Array) -> jax.Array:
    h = (x.astype(jnp.float32) - params["norm"]["mu"]) / params["norm"]["sigma"]
    layers = params["layers"]
    for layer in layers[:-1]:
        h = jax.nn.relu(_q_dense(h, layer))
    return _q_dense(h, layers[-1]).reshape(x.shape[0])


@jax.jit
def apply(params: Params, x: jax.Array) -> jax.Array:
    """proba_1 per row: (B, F) -> (B,), int8 matmuls on the MXU."""
    return jax.nn.sigmoid(logits(params, x))


def apply_numpy(params: Params, x: np.ndarray) -> np.ndarray:
    """Host-tier forward with the SAME quantized math (int32 accumulate)."""
    from ccfd_tpu.utils.metrics_math import stable_sigmoid

    h = (np.asarray(x, np.float32) - np.asarray(params["norm"]["mu"])) / np.asarray(
        params["norm"]["sigma"]
    )
    layers = params["layers"]
    for li, layer in enumerate(layers):
        amax = np.abs(h).max(axis=1)
        s_x = np.maximum(amax / 127.0, _EPS)
        q = np.clip(np.rint(h / s_x[:, None]), -127, 127).astype(np.int8)
        acc = q.astype(np.int32) @ np.asarray(layer["wq"], np.int32)
        # scales combine FIRST: with a degenerate (activation-exploding)
        # model, acc * s_x can overflow float32 to inf and a zero weight
        # channel (scale 0) then turns it into nan (inf * 0); the combined
        # per-(row, channel) scale keeps every factor finite, and the clamp
        # stops an inf from one layer poisoning the next layer's s_x.
        # For healthy models both are no-ops modulo float rounding.
        h = acc.astype(np.float32) * (
            s_x[:, None] * np.asarray(layer["scale"], np.float32)[None, :]
        ) + np.asarray(layer["b"], np.float32)
        h = np.clip(h, -_H_CLAMP, _H_CLAMP)
        if li < len(layers) - 1:
            h = np.maximum(h, 0.0)
    return stable_sigmoid(h.reshape(x.shape[0]))


def register(base_params: Params | None = None) -> None:
    """Register the quantized graph as model ``mlp_q8``.

    ``init`` quantizes a fresh (or provided) f32 MLP so ``Scorer(
    model_name="mlp_q8")`` works standalone; production flows call
    ``quantize_mlp`` on trained params and pass them explicitly.
    """
    from ccfd_tpu.models import mlp
    from ccfd_tpu.models.registry import ModelSpec, register_model

    def init(key=None, **kw):
        p = base_params if base_params is not None else mlp.init(
            key if key is not None else jax.random.PRNGKey(0), **kw
        )
        if "norm" not in p:
            p = mlp.set_normalizer(
                p, np.zeros(p["layers"][0]["w"].shape[0], np.float32),
                np.ones(p["layers"][0]["w"].shape[0], np.float32),
            )
        return quantize_mlp(p)

    register_model(
        ModelSpec("mlp_q8", init, apply, logits, trainable=False,
                  apply_numpy=apply_numpy)
    )
