"""Ulysses-style all-to-all sequence parallelism: the second long-context
strategy next to ring attention (ops/ring_attention.py).

The reference has no sequence dimension (SURVEY.md §5 "long-context: N/A"),
but this framework treats long-context as first-class. Two exact-attention
shardings over per-customer transaction histories, chosen by regime:

- **Ring** (ring_attention): K/V shards rotate around the mesh axis with
  ``ppermute`` (neighbor ICI hops), online-softmax accumulation. Peak
  memory O(L_local) per device; n_devices pipeline steps. The choice for
  EXTREME sequence lengths.
- **Ulysses** (this module): two ``all_to_all`` reshards. The sequence
  axis is traded for the head axis — each device goes from holding all
  heads of its L/n sequence shard to holding H/n heads of the FULL
  sequence — then attention runs locally as ONE dense einsum (best MXU
  utilization, no scan), and a reverse all-to-all restores the sequence
  sharding. Communication is 2 all-to-alls over q/k/v/out instead of n-1
  ppermute rounds; memory holds (B, H/n, L, L) scores, so it is the
  choice when L is moderate and heads are plentiful (H % n == 0).

Both ops share one contract: (B, H, L, D) in and out, sequence axis
sharded over the named mesh axis, non-causal (histories attend
bidirectionally), exact softmax attention (parity-tested against the
single-device reference and each other).
"""

from __future__ import annotations

from functools import partial

import jax
from jax.sharding import Mesh, PartitionSpec as P

from ccfd_tpu.ops.ring_attention import reference_attention
from ccfd_tpu.ops.shard_compat import shard_map


def _ulysses_body(q, k, v, axis_name: str):
    """Per-device program. Local shapes: (B, H, L/n, D) in and out."""
    # resharding all-to-all: scatter heads (axis 1), gather sequence
    # (axis 2) -> (B, H/n, L, D) per device, full sequence locally
    a2a = partial(
        jax.lax.all_to_all, axis_name=axis_name, split_axis=1, concat_axis=2,
        tiled=True,
    )
    qh, kh, vh = a2a(q), a2a(k), a2a(v)
    # full attention over the device's head group: one dense einsum on
    # the MXU — this is the whole point of trading L-sharding for
    # H-sharding
    oh = reference_attention(qh, kh, vh)
    # reverse reshard: scatter sequence, gather heads -> (B, H, L/n, D)
    return jax.lax.all_to_all(
        oh, axis_name=axis_name, split_axis=2, concat_axis=1, tiled=True
    )


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis_name: str,
) -> jax.Array:
    """Exact attention with L sharded over ``axis_name``. (B, H, L, D) in/out.

    Requires H and L both divisible by the axis size (the all-to-alls
    redistribute heads across devices and sequence across the local dim).
    """
    n = mesh.shape[axis_name]
    if q.shape[1] % n:
        raise ValueError(
            f"ulysses_attention needs heads ({q.shape[1]}) divisible by "
            f"mesh axis {axis_name!r} size ({n}); use ring_attention for "
            f"head counts below the axis size"
        )
    if q.shape[2] % n:
        raise ValueError(
            f"sequence length {q.shape[2]} must divide evenly over mesh "
            f"axis {axis_name!r} size ({n})"
        )
    spec = P(None, None, axis_name, None)
    fn = shard_map(
        partial(_ulysses_body, axis_name=axis_name),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
