"""Pallas TPU kernel: fully fused MLP fraud scoring.

The serving hot op is tiny-model/huge-batch: a 3-layer MLP whose weights
(~0.3 MB in bf16) fit in VMEM many times over, fed with tens of thousands
of 30-feature rows per dispatch. The fused kernel:

- keeps ALL weights resident in VMEM for the whole grid (BlockSpecs with a
  constant index map), so HBM traffic is exactly one read of x and one
  write of the probabilities — the theoretical minimum;
- normalization is pre-folded into W1/b1 (an affine composed with an
  affine), so the kernel body is 3 matmuls + 2 relus + a sigmoid on the
  VPU/MXU with zero intermediate HBM round-trips;
- features are zero-padded 30 -> 128 host-side once (weights likewise), so
  every matmul is exactly lane-aligned (128-wide) for the MXU;
- the grid tiles the batch; each program scores a (TILE, 128) slab in
  bfloat16 with float32 accumulation.

On non-TPU backends the same kernel runs under ``interpret=True`` so tests
exercise identical code paths on the CPU mesh (SURVEY.md §4).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from ccfd_tpu.data.ccfd import NUM_FEATURES

LANE = 128  # TPU lane width: last-dim alignment target
DEFAULT_TILE = 512
INPUT_DTYPE = "bfloat16"  # wire format for rows: half the H2D bytes


def fit_tile(rows: int) -> int:
    """Largest power-of-two-ish tile <= DEFAULT_TILE dividing ``rows`` —
    the ONE tiling policy every caller (both kernels' dispatch paths and
    the bench) shares."""
    tile = min(rows, DEFAULT_TILE)
    while rows % tile:
        tile //= 2
    return tile


def _pad_to(a: np.ndarray, rows: int) -> np.ndarray:
    pad = rows - a.shape[0]
    if pad <= 0:
        return a
    return np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)], axis=0)


def fold_for_kernel(params: Mapping[str, Any]) -> dict[str, jax.Array]:
    """MLP params (ccfd_tpu.models.mlp layout) -> kernel weights.

    Folds the standardizer into layer 0 and zero-pads the feature dim to the
    TPU lane width: with s = 1/sigma, (x - mu) * s @ W1 + b1 ==
    x @ (s[:, None] * W1) + (b1 - (mu * s) @ W1).
    """
    mu = np.asarray(params["norm"]["mu"], np.float32)
    sigma = np.asarray(params["norm"]["sigma"], np.float32)
    s = 1.0 / np.where(sigma == 0.0, 1.0, sigma)
    layers = params["layers"]
    if len(layers) != 3:
        raise ValueError("fused kernel expects a 3-layer MLP")
    w1 = np.asarray(layers[0]["w"], np.float32)
    b1 = np.asarray(layers[0]["b"], np.float32)
    w1_folded = s[:, None] * w1
    b1_folded = b1 - (mu * s) @ w1
    return {
        "w1": jnp.asarray(_pad_to(w1_folded, LANE)),  # (128, H)
        "b1": jnp.asarray(b1_folded),
        "w2": jnp.asarray(np.asarray(layers[1]["w"], np.float32)),
        "b2": jnp.asarray(np.asarray(layers[1]["b"], np.float32)),
        "w3": jnp.asarray(np.asarray(layers[2]["w"], np.float32)),  # (H, 1)
        "b3": jnp.asarray(np.asarray(layers[2]["b"], np.float32)),
    }


# ccfd-lint: hot-path
def _kernel(x_ref, w1_ref, b1_ref, w2_ref, b2_ref, w3_ref, b3_ref, out_ref):
    x = x_ref[:].astype(jnp.bfloat16)
    h = jnp.dot(x, w1_ref[:].astype(jnp.bfloat16), preferred_element_type=jnp.float32)
    h = jnp.maximum(h + b1_ref[:], 0.0).astype(jnp.bfloat16)
    h = jnp.dot(h, w2_ref[:].astype(jnp.bfloat16), preferred_element_type=jnp.float32)
    h = jnp.maximum(h + b2_ref[:], 0.0).astype(jnp.bfloat16)
    # final layer as an elementwise reduce: (T, H) * (H,) -> (T, 1)
    w3 = w3_ref[:].astype(jnp.bfloat16).reshape(1, -1)
    z = jnp.sum(
        h.astype(jnp.float32) * w3.astype(jnp.float32), axis=1, keepdims=True
    )
    out_ref[:] = jax.nn.sigmoid(z + b3_ref[:])


def pad_features(x: jax.Array) -> jax.Array:
    """(B, F) -> (B, 128) zero-padded."""
    b, f = x.shape
    if f == LANE:
        return x
    return jnp.pad(x, ((0, 0), (0, LANE - f)))


@partial(jax.jit, static_argnames=("tile", "interpret"))
# ccfd-lint: hot-path
def fused_mlp_score(
    kernel_params: Mapping[str, jax.Array],
    x: jax.Array,
    tile: int = DEFAULT_TILE,
    interpret: bool = False,
) -> jax.Array:
    """(B, F<=128) float or bfloat16 -> (B,) float32 proba. B must be a tile
    multiple. bfloat16 input is the fast path: the kernel computes in bf16
    regardless, and bf16 rows halve the host->HBM transfer — on serving
    setups where the wire dominates (tunneled chips, DCN-remote hosts) that
    is ~2x end-to-end throughput for identical numerics."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if x.dtype != jnp.bfloat16:
        x = x.astype(jnp.float32)
    x = pad_features(x)
    batch = x.shape[0]
    if batch % tile != 0:
        raise ValueError(f"batch {batch} not a multiple of tile {tile}")
    hidden = kernel_params["w2"].shape[0]
    grid = (batch // tile,)

    def xmap(i):
        return (i, 0)

    def const(i):
        return (0, 0)

    mem = pltpu.VMEM  # weights resident in VMEM for the whole grid

    out = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((batch, 1), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, LANE), xmap, memory_space=mem),
            pl.BlockSpec((LANE, hidden), const, memory_space=mem),
            pl.BlockSpec((hidden,), lambda i: (0,), memory_space=mem),
            pl.BlockSpec((hidden, hidden), const, memory_space=mem),
            pl.BlockSpec((hidden,), lambda i: (0,), memory_space=mem),
            pl.BlockSpec((hidden, 1), const, memory_space=mem),
            pl.BlockSpec((1,), lambda i: (0,), memory_space=mem),
        ],
        out_specs=pl.BlockSpec((tile, 1), xmap, memory_space=mem),
        interpret=interpret,
    )(
        x,
        kernel_params["w1"],
        kernel_params["b1"],
        kernel_params["w2"],
        kernel_params["b2"],
        kernel_params["w3"],
        kernel_params["b3"],
    )
    return out.reshape(batch)


# uniform entry point for Scorer's fused-module dispatch (the q8 sibling
# ccfd_tpu/ops/fused_mlp_q8.py exposes the same name)
fused_score = fused_mlp_score


def make_score_fn(params: Mapping[str, Any], tile: int = DEFAULT_TILE):
    """Returns proba_fn(x_padded_batch) using the fused kernel; interpret mode
    is selected automatically off-TPU."""
    kp = fold_for_kernel(params)
    # Mosaic lowering needs real TPU hardware; everywhere else (the CPU test
    # mesh) the interpreter runs the identical kernel body.
    interpret = jax.default_backend() == "cpu"

    def score(x: jax.Array) -> jax.Array:
        return fused_mlp_score(kp, x, tile=tile, interpret=interpret)

    return score
