"""Pallas TPU kernel: fully fused int8-quantized MLP fraud scoring.

The int8 sibling of :mod:`ccfd_tpu.ops.fused_mlp`: same tiny-model/
huge-batch serving shape (weights resident in VMEM for the whole grid, one
HBM read of x and one write of the probabilities), but the two hidden
matmuls run int8 x int8 -> int32 on the MXU — the mode the systolic array
executes at up to twice the bf16 rate — and the weights sit in VMEM at a
quarter of f32.

The math is EXACTLY :func:`ccfd_tpu.ops.quant.logits` (the served XLA
``mlp_q8`` graph): normalize f32 -> per-row symmetric int8 requantization
before every layer -> int32 accumulate -> f32 dequant + bias (+ relu).
Differences from the XLA graph are layout only:

- activations never round-trip to HBM between layers (the XLA path
  materializes each layer's output);
- the last layer's int math runs elementwise on the VPU in f32: products
  of two int8 values and their 256-term partial sums are integers below
  2^24, all exactly representable in f32, so the result equals the XLA
  path's int32 accumulate bit-for-bit before the final dequant;
- rows ship as f32, exactly like the XLA path receives them, so the
  kernel is numerically indistinguishable from the served graph
  (max prob delta ~1e-7, asserted in tests/test_fused_q8.py).  bf16 rows
  would halve H2D bytes but double the effective quantization noise
  (measured 0.058 max prob delta vs the XLA graph) — the int8 path's
  accuracy budget is already spent on weight+activation quantization, so
  the wire keeps f32.

On non-TPU backends the kernel runs under ``interpret=True`` so the CPU
test mesh exercises the identical body (SURVEY.md §4).

Reference parity context: the quantized graph serves the same Seldon
REST contract as the reference's ``modelfull``
(/root/reference/deploy/model/modelfull.json:37-44); quantization itself
has no reference analog — it exists for the TPU serving regime.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np

# geometry and the feature/row pad helpers are the bf16 kernel's — one
# source of truth for the lane width and tiling defaults
from ccfd_tpu.ops.fused_mlp import (  # noqa: E402
    DEFAULT_TILE,
    LANE,
    _pad_to as _pad_rows,
    fit_tile,
    pad_features,
)

INPUT_DTYPE = "float32"  # wire format for rows: exact parity with XLA q8
_EPS = 1e-8


def fold_for_kernel(params: Mapping[str, Any]) -> dict[str, jax.Array]:
    """quantized MLP params (ops/quant.py layout) -> kernel weights.

    The normalizer CANNOT be folded into int8 weights the way the f32
    kernel folds it (per-input scaling would break the per-output-channel
    quantization grid), so mu / sigma ride along as f32 vectors and the
    kernel normalizes explicitly — as a DIVISION by raw sigma, exactly
    like quant.logits: multiplying by a precomputed reciprocal differs in
    the last ulp, which can flip a quantization step at a rounding
    boundary (measured: up to 4e-3 prob delta on large-magnitude
    normalizers).  Padded feature columns get mu = 0 / sigma = 1, so
    padded features normalize to exactly 0 and the zero-padded rows of
    w1q contribute exactly 0 to the accumulate.
    """
    layers = params["layers"]
    if len(layers) != 3 or "wq" not in layers[0]:
        raise KeyError("fused q8 kernel expects a 3-layer quantized MLP")
    mu = np.asarray(params["norm"]["mu"], np.float32)
    sigma = np.asarray(params["norm"]["sigma"], np.float32)
    n_feat = mu.shape[0]
    if n_feat > LANE:
        raise ValueError(f"{n_feat} features > lane width {LANE}")
    w1q = np.asarray(layers[0]["wq"], np.int8)
    if w1q.shape[0] != n_feat:
        raise ValueError("normalizer/layer-0 feature-count mismatch")
    # w3 as f32: int8 products and their partial sums stay integer-exact
    # in f32 (< 2^24), see module docstring.  That bound holds only while
    # hidden <= 2^24 / 127^2 = 1040; the C++ front refuses wider models at
    # install (httpfront.cpp ccfd_front_set_host_q8_model) and the kernel
    # must refuse them too — hiddens are multiples of 128, so 1152+ is a
    # legal config that would silently break the asserted bit-parity with
    # the XLA int32 accumulate (ADVICE r4).
    hidden_last = int(np.asarray(layers[2]["wq"]).shape[0])
    if hidden_last > 1040:
        raise ValueError(
            f"fused q8 kernel: last-layer input width {hidden_last} > 1040 "
            "breaks the integer-exact f32 accumulate (2^24 bound); "
            "serve this model via the XLA mlp_q8 graph instead")
    w3f = np.asarray(layers[2]["wq"], np.float32).reshape(1, -1)
    return {
        "mu": jnp.asarray(np.pad(mu, (0, LANE - n_feat))),
        "sigma": jnp.asarray(np.pad(sigma, (0, LANE - n_feat),
                                    constant_values=1.0)),
        "w1q": jnp.asarray(_pad_rows(w1q, LANE)),  # (128, H) int8
        "s1": jnp.asarray(np.asarray(layers[0]["scale"], np.float32)),
        "b1": jnp.asarray(np.asarray(layers[0]["b"], np.float32)),
        "w2q": jnp.asarray(np.asarray(layers[1]["wq"], np.int8)),  # (H, H)
        "s2": jnp.asarray(np.asarray(layers[1]["scale"], np.float32)),
        "b2": jnp.asarray(np.asarray(layers[1]["b"], np.float32)),
        "w3f": jnp.asarray(w3f),  # (1, H) f32 holding int8 values
        "s3": jnp.asarray(np.asarray(layers[2]["scale"], np.float32)),
        "b3": jnp.asarray(np.asarray(layers[2]["b"], np.float32)),
    }


def _rowquant(h: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-row symmetric int8 (same math as quant._quantize_rows)."""
    amax = jnp.max(jnp.abs(h), axis=1, keepdims=True)
    s = jnp.maximum(amax / 127.0, _EPS)
    q = jnp.clip(jnp.rint(h / s), -127, 127).astype(jnp.int8)
    return q, s


def _kernel(x_ref, mu_ref, sigma_ref, w1_ref, s1_ref, b1_ref,
            w2_ref, s2_ref, b2_ref, w3_ref, s3_ref, b3_ref, out_ref):
    x = x_ref[:].astype(jnp.float32)
    h = (x - mu_ref[:]) / sigma_ref[:]
    # layer 1: int8 MXU matmul, int32 accumulate
    q, sx = _rowquant(h)
    acc = jnp.dot(q, w1_ref[:], preferred_element_type=jnp.int32)
    h = jnp.maximum(acc.astype(jnp.float32) * sx * s1_ref[:] + b1_ref[:], 0.0)
    # layer 2
    q, sx = _rowquant(h)
    acc = jnp.dot(q, w2_ref[:], preferred_element_type=jnp.int32)
    h = jnp.maximum(acc.astype(jnp.float32) * sx * s2_ref[:] + b2_ref[:], 0.0)
    # layer 3 as an integer-exact f32 elementwise reduce on the VPU
    q, sx = _rowquant(h)
    z = jnp.sum(q.astype(jnp.float32) * w3_ref[:], axis=1, keepdims=True)
    out_ref[:] = jax.nn.sigmoid(z * sx * s3_ref[:] + b3_ref[:])


def _xmap(i):
    return (i, 0)


def _const2(i):
    return (0, 0)


def _const1(i):
    return (0,)


def _call_kernel(kernel_fn, lead_kinds, lead_arrays, kernel_params,
                 tile, interpret):
    """Shared pallas_call scaffolding for both q8 entry points: the lead
    inputs differ, the 9 VMEM-resident weight specs do not.

    ``lead_kinds``: one entry per lead array — ``("tiled", width)`` for a
    batch-tiled (tile, width) block, ``("const", length)`` for a
    grid-constant 1-D vector.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch = lead_arrays[0].shape[0]
    if batch % tile != 0:
        raise ValueError(f"batch {batch} not a multiple of tile {tile}")
    hidden = kernel_params["w2q"].shape[0]
    mem = pltpu.VMEM  # weights resident in VMEM for the whole grid
    lead_specs = [
        pl.BlockSpec((tile, dim), _xmap, memory_space=mem)
        if kind == "tiled"
        else pl.BlockSpec((dim,), _const1, memory_space=mem)
        for kind, dim in lead_kinds
    ]
    weight_specs = [
        pl.BlockSpec((LANE, hidden), _const2, memory_space=mem),
        pl.BlockSpec((hidden,), _const1, memory_space=mem),
        pl.BlockSpec((hidden,), _const1, memory_space=mem),
        pl.BlockSpec((hidden, hidden), _const2, memory_space=mem),
        pl.BlockSpec((hidden,), _const1, memory_space=mem),
        pl.BlockSpec((hidden,), _const1, memory_space=mem),
        pl.BlockSpec((1, hidden), _const2, memory_space=mem),
        pl.BlockSpec((1,), _const1, memory_space=mem),
        pl.BlockSpec((1,), _const1, memory_space=mem),
    ]
    out = pl.pallas_call(
        kernel_fn,
        out_shape=jax.ShapeDtypeStruct((batch, 1), jnp.float32),
        grid=(batch // tile,),
        in_specs=lead_specs + weight_specs,
        out_specs=pl.BlockSpec((tile, 1), _xmap, memory_space=mem),
        interpret=interpret,
    )(
        *lead_arrays,
        kernel_params["w1q"],
        kernel_params["s1"],
        kernel_params["b1"],
        kernel_params["w2q"],
        kernel_params["s2"],
        kernel_params["b2"],
        kernel_params["w3f"],
        kernel_params["s3"],
        kernel_params["b3"],
    )
    return out.reshape(batch)


@partial(jax.jit, static_argnames=("tile", "interpret"))
def fused_mlp_q8_score(
    kernel_params: Mapping[str, jax.Array],
    x: jax.Array,
    tile: int = DEFAULT_TILE,
    interpret: bool = False,
) -> jax.Array:
    """(B, F<=128) rows -> (B,) float32 proba.  B must be a tile multiple.
    f32 rows are the contract (exact parity with the XLA q8 graph); other
    float dtypes are accepted and widened/rounded to f32 first — including
    bf16, whose widening is lossless and keeps the kernel on one wire
    dtype (a bf16 fast path here would silently ship the degraded
    0.058-max-prob-delta behavior the module docstring warns against,
    with stricter sublane tiling on small fit_tile values; ADVICE r4)."""
    x = x.astype(jnp.float32)
    x = pad_features(x)
    return _call_kernel(
        _kernel,
        [("tiled", LANE), ("const", LANE), ("const", LANE)],
        (x, kernel_params["mu"], kernel_params["sigma"]),
        kernel_params, tile, interpret,
    )


# uniform entry point for Scorer's fused-module dispatch
fused_score = fused_mlp_q8_score


# ---------------------------------------------------------------------------
# int8-at-the-edge wire path: the host normalizes and row-quantizes, rows
# ship as int8 + one f32 scale each (34 B/row vs 120 B f32, 3.5x fewer
# H2D bytes), and the kernel starts straight at the first MXU matmul.
# Bit-identical to the full kernel / XLA graph: the host performs the
# model's OWN first requantization, just on the other side of the wire.
# On a tunneled attachment where H2D dominates the serving hop (the
# reason the bf16 kernel ships bf16 rows), this is the q8 path's wire
# lever; the numpy quantize cost rides the host, so the tradeoff is
# attachment-specific and recorded by the bench quant section, not
# assumed.
# ---------------------------------------------------------------------------


def prequantize_rows_numpy(
    kernel_params: Mapping[str, Any], x: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Host-side normalize + per-row symmetric int8 quantization.

    (B, F<=128) f32 rows -> ((B, F) int8, (B, 1) f32 scales), the exact
    math of the kernel's own first _rowquant (and quant._quantize_rows).
    The int8 rows stay UNPADDED — the wire carries F bytes per row; the
    device pads to the lane width inside the jit (padded columns quantize
    to exactly 0 either way, so the scales are unaffected).
    """
    mu = np.asarray(kernel_params["mu"], np.float32)
    sigma = np.asarray(kernel_params["sigma"], np.float32)
    x = np.asarray(x, np.float32)
    n_feat = x.shape[1]
    # DIVISION by raw sigma, exactly like quant.logits (see fold_for_kernel)
    h = (x - mu[:n_feat]) / sigma[:n_feat]
    amax = np.max(np.abs(h), axis=1, keepdims=True)
    s = np.maximum(amax / 127.0, _EPS).astype(np.float32)
    q = np.clip(np.rint(h / s), -127, 127).astype(np.int8)
    return q, s


def _kernel_preq(q_ref, s_ref, w1_ref, s1_ref, b1_ref,
                 w2_ref, s2_ref, b2_ref, w3_ref, s3_ref, b3_ref, out_ref):
    sx = s_ref[:]
    acc = jnp.dot(q_ref[:], w1_ref[:], preferred_element_type=jnp.int32)
    h = jnp.maximum(acc.astype(jnp.float32) * sx * s1_ref[:] + b1_ref[:], 0.0)
    q, sx = _rowquant(h)
    acc = jnp.dot(q, w2_ref[:], preferred_element_type=jnp.int32)
    h = jnp.maximum(acc.astype(jnp.float32) * sx * s2_ref[:] + b2_ref[:], 0.0)
    q, sx = _rowquant(h)
    z = jnp.sum(q.astype(jnp.float32) * w3_ref[:], axis=1, keepdims=True)
    out_ref[:] = jax.nn.sigmoid(z * sx * s3_ref[:] + b3_ref[:])


@partial(jax.jit, static_argnames=("tile", "interpret"))
def fused_mlp_q8_score_preq(
    kernel_params: Mapping[str, jax.Array],
    q: jax.Array,
    s: jax.Array,
    tile: int = DEFAULT_TILE,
    interpret: bool = False,
) -> jax.Array:
    """((B, F<=128) int8 rows, (B, 1) f32 scales) -> (B,) float32 proba.
    Rows are padded to the lane width on DEVICE, so the H2D wire carries
    only F int8 bytes per row (34 B/row vs f32's 120 at F=30)."""
    if q.dtype != jnp.int8:
        raise ValueError("q must be int8 rows (see prequantize_rows_numpy)")
    q = pad_features(q)
    return _call_kernel(
        _kernel_preq, [("tiled", LANE), ("tiled", 1)], (q, s),
        kernel_params, tile, interpret,
    )
