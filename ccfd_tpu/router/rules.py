"""Declarative decision rules — the Drools capability, batch-vectorized.

The reference router embeds a Drools rule base: the returned fraud
probability is matched against ``FRAUD_THRESHOLD`` and the winning rule
decides which business process to start (reference deploy/router.yaml:69-70,
README.md:424-459 "applies some business rules (using Drools) to the
prediction"). Drools evaluates per-fact with salience-ordered activation;
that per-message shape is exactly what the TPU pipeline must avoid.

Re-design: a rule base is a *vectorized classifier over the micro-batch*.
Every rule's LHS (a conjunction of comparisons over the 30 tx features and
the model probability) evaluates as one boolean mask over the whole (B,)
batch; salience order + first-match-wins assigns each row its action. The
masks are plain numpy on the already-host-resident feature matrix — after
the TPU scoring dispatch there is nothing left but (B,) comparisons, and
keeping them on host avoids a second device round-trip for work the VPU
would finish before the dispatch overhead cleared.

Rule bases load from JSON (``CCFD_RULES``), so operators can change routing
policy without touching code — the same knob the reference exposes by
rebuilding the Drools KJAR. ``default_rules()`` reproduces the reference
semantics bit-for-bit: ``proba >= FRAUD_THRESHOLD -> fraud, else standard``.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Mapping, Sequence

import numpy as np

from ccfd_tpu.data.ccfd import FEATURE_NAMES

PROBA_FIELD = "proba"
_OP_FUNCS = {
    ">": np.greater,
    ">=": np.greater_equal,
    "<": np.less,
    "<=": np.less_equal,
    "==": np.equal,
    "!=": np.not_equal,
}
_OPS = (*_OP_FUNCS, "between")


@dataclass(frozen=True)
class Condition:
    """One comparison: ``field op value`` over a feature column or ``proba``."""

    fld: str
    op: str
    value: Any

    def __post_init__(self):
        if self.op not in _OPS:
            raise ValueError(f"unknown op {self.op!r}; known: {_OPS}")
        if self.fld != PROBA_FIELD and self.fld not in FEATURE_NAMES:
            raise ValueError(
                f"unknown field {self.fld!r}; expected {PROBA_FIELD!r} or a "
                f"feature name"
            )
        if self.op == "between":
            if (
                isinstance(self.value, (str, bytes))
                or not isinstance(self.value, Sequence)
                or len(self.value) != 2
                or any(isinstance(v, (str, bytes)) for v in self.value)
            ):
                raise ValueError("'between' needs value [lo, hi] (numeric)")
            for v in self.value:
                float(v)
        elif isinstance(self.value, (str, bytes)):
            raise ValueError(f"non-numeric value {self.value!r}")
        else:
            float(self.value)  # must be numeric

    def mask(self, x: np.ndarray, proba: np.ndarray) -> np.ndarray:
        col = (
            proba
            if self.fld == PROBA_FIELD
            else x[:, FEATURE_NAMES.index(self.fld)]
        )
        if self.op == "between":
            lo, hi = (col.dtype.type(v) for v in self.value)
            return (col >= lo) & (col <= hi)
        # cast the operand to the column dtype: comparing a float32 column
        # against a float64 literal would make ==/!= on non-dyadic values
        # (0.1, ...) silently never/always match
        v = col.dtype.type(self.value)
        return _OP_FUNCS[self.op](col, v)


@dataclass(frozen=True)
class Rule:
    """LHS = conjunction of conditions; RHS = start ``process`` with vars.

    ``when_fn`` (programmatic rule bases only — JSON cannot carry code):
    an arbitrary ``(x, proba) -> (B,) bool`` predicate AND-ed with the
    declarative conditions. The escape hatch for policies the Condition
    grammar cannot express — but it is host-only: a rule base with ANY
    ``when_fn`` cannot compile to the fused decision kernel's predicate
    tensors, and the whole set serves the staged path with one loud
    warning (ops/fused_decision.py compile_rules). Never a per-row split.
    """

    name: str
    process: str
    when: tuple[Condition, ...] = ()
    salience: int = 0
    set_vars: Mapping[str, Any] = field(default_factory=dict)
    when_fn: Any = None

    def __post_init__(self):
        if self.when_fn is not None and not callable(self.when_fn):
            raise ValueError(
                f"rule {self.name!r}: when_fn must be callable "
                f"(x, proba) -> bool mask, got {type(self.when_fn).__name__}"
            )

    def mask(self, x: np.ndarray, proba: np.ndarray) -> np.ndarray:
        m = np.ones(proba.shape[0], bool)
        for c in self.when:
            m &= c.mask(x, proba)
        if self.when_fn is not None:
            m &= np.asarray(self.when_fn(x, proba), bool)
        return m


class RuleSet:
    """Salience-ordered, first-match-wins rule base over a scored batch."""

    def __init__(self, rules: Sequence[Rule]):
        if not rules:
            raise ValueError("empty rule base")
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names: {sorted(names)}")
        # stable sort: equal salience keeps authoring order, like Drools
        self.rules: tuple[Rule, ...] = tuple(
            sorted(rules, key=lambda r: -r.salience)
        )
        if not any(not r.when for r in self.rules):
            raise ValueError(
                "no default rule (empty 'when'): some rows would match nothing"
            )

    def evaluate(self, x: np.ndarray, proba: np.ndarray) -> np.ndarray:
        """(B,30) features + (B,) probabilities -> (B,) rule indices.

        One boolean-mask pass per rule over the whole batch; a row takes the
        highest-salience rule whose conjunction holds.
        """
        proba = np.asarray(proba)
        assigned = np.full(proba.shape[0], -1, np.int64)
        for i, rule in enumerate(self.rules):
            m = rule.mask(x, proba) & (assigned < 0)
            assigned[m] = i
        return assigned  # always >=0: a default rule matches everything

    # -- serialization -----------------------------------------------------

    @staticmethod
    def from_obj(obj: Sequence[Mapping[str, Any]]) -> "RuleSet":
        rules = []
        for r in obj:
            rules.append(
                Rule(
                    name=str(r["name"]),
                    process=str(r["process"]),
                    when=tuple(
                        Condition(str(c["field"]), str(c["op"]), c["value"])
                        for c in r.get("when", ())
                    ),
                    salience=int(r.get("salience", 0)),
                    set_vars=dict(r.get("set_vars", {})),
                )
            )
        return RuleSet(rules)

    @staticmethod
    def from_file(path: str) -> "RuleSet":
        with open(path) as f:
            return RuleSet.from_obj(json.load(f))


def default_rules(fraud_threshold: float) -> RuleSet:
    """The reference's embedded Drools base (router.yaml:69-70): probability
    at or above FRAUD_THRESHOLD starts the fraud process, otherwise the
    standard process."""
    return RuleSet(
        [
            Rule(
                "fraud",
                process="fraud",
                when=(Condition(PROBA_FIELD, ">=", fraud_threshold),),
                salience=10,
            ),
            Rule("standard", process="standard"),
        ]
    )
