"""Partition-parallel router fan-out with shared coalesced device dispatch.

One :class:`~ccfd_tpu.router.router.Router` thread consumes every bus
partition and serializes decode + engine hand-off even in the pipelined
loop — ``bench.py``'s ``pipeline`` section sustains a fraction of what the
same scorer does alone. The reference scales this exact hop by Kafka
partitions × router replicas (reference deploy/frauddetection_cr.yaml
partitions, router.yaml replicas); the TPU-native analog is many consumer
workers feeding ONE accelerator through a coalescing batcher — the
"300M predictions/sec" pattern (arXiv:2109.09541), with the batch/deadline
budget SLO-bounded rather than fixed (InferLine, arXiv:1812.01776).

:class:`ParallelRouter` runs N worker loops (default = the transaction
topic's partition count; ``CCFD_ROUTER_WORKERS`` overrides under the
operator/CLI roles). Each worker is a full Router running the existing
pipelined poll→decode→dispatch→route stages and owning a disjoint
partition subset via ordinary consumer-group assignment — per-partition
ordering is therefore preserved by construction: a partition has exactly
one consuming worker, and that worker routes its batches in poll order.

What the workers SHARE is the control plane:

- **One device scorer behind a coalescing batcher** (serving/batcher.py
  DynamicBatcher): concurrent workers' sub-batches merge into one bucketed
  device dispatch — the same amortization the REST path gets — with the
  batcher's deadline bounding how long a lone worker's batch can wait for
  stragglers. ``router_coalesced_dispatches_total`` /
  ``router_coalesced_rows_total`` against ``router_worker_batches_total``
  show the fan-in. History-aware scorers (``score_with_ids``) bypass
  coalescing: their per-customer state keys on the decoded records, which
  a row-concatenating batcher cannot carry.
- **One in-flight budget** (router.InflightBudget): the bounded-in-flight
  shedding bound holds across ALL workers — N workers cannot hold N× the
  configured budget.
- **One circuit breaker** on the scorer edge (when the degradation ladder
  is on): the edge is shared, so its health accounting must be too.
- **One engine**: hand-off stays race-free because the Engine serializes
  every public entry point under its own RLock (process/engine.py) — the
  documented locked path; per-partition sharding is unnecessary because
  batched starts already amortize the lock per micro-batch, not per
  transaction.
- **A group-wide pause barrier**: ``pause()`` requests every worker's
  hold FIRST, then awaits all acks, so the checkpoint coordinator
  (runtime/recovery.py) sees the same guarantee as with one router —
  every consumed record fully routed, nothing in flight anywhere — before
  it reads an aligned cut.

Per-worker observability: each worker's batches are labelled
``router_worker_batches_total{worker=i}`` and its ``router.batch`` spans
carry a ``worker`` attr, so the PR-2 per-stage trace attribution survives
the fan-out.

The facade mirrors the Router surface the rest of the runtime touches
(pause/resume/recycle_consumers/swap_engine/engine/run/start/stop/close/
step and the ``_stop`` liveness flag), so the CheckpointCoordinator, the
Supervisor, the ChaosMonkey and the soak/bench tools drive it unchanged.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Mapping

import numpy as np

from ccfd_tpu.bus.broker import Broker
from ccfd_tpu.config import Config
from ccfd_tpu.metrics.prom import Registry
from ccfd_tpu.router.router import EngineClient, InflightBudget, Router
from ccfd_tpu.router.rules import RuleSet


class ParallelRouter:
    def __init__(
        self,
        cfg: Config,
        broker: Broker,
        score_fn: Callable[[np.ndarray], np.ndarray],
        engine: EngineClient,
        registry: Registry | None = None,
        workers: int = 0,
        max_batch: int = 4096,
        rules: RuleSet | None = None,
        host_score_fn: Callable[[np.ndarray], np.ndarray] | None = None,
        breaker: "Any | None" = None,
        degrade: bool | None = None,
        max_inflight: int | None = None,
        tracer: "Any | None" = None,
        coalesce: bool = True,
        coalesce_max_batch: int | None = None,
        coalesce_deadline_ms: float | None = None,
        coalesce_workers: int = 2,
        overload: "Any | None" = None,
        profiler: "Any | None" = None,
        heal_gate: "Any | None" = None,
        audit: "Any | None" = None,
        commit_after_route: bool = False,
        decision_fn: "Any | None" = None,
    ):
        self.cfg = cfg
        self.broker = broker
        self.registry = registry or Registry()
        self.max_batch = max_batch
        if workers <= 0:
            workers = max(1, len(broker.end_offsets(cfg.kafka_topic)))
        self.n_workers = workers

        # -- shared in-flight budget (the global bound, not per worker) ----
        # An EXPLICIT max_inflight is a global statement: N workers share
        # it and cannot hold N× it. The default scales with the pool —
        # each worker's pipelined steady state legitimately holds up to
        # 2×max_batch (one batch in flight + one fresh poll), so the
        # pool-wide default is 2×max_batch×workers: healthy operation
        # never sheds, exactly like the single-router default.
        #
        # With an OverloadControl (runtime/overload.py) the pool shares
        # ITS adaptive AIMD budget instead: one limit, moved by every
        # worker's scorer-latency observations, bounding the whole pool —
        # the same global-across-workers semantics, made dynamic.
        self._overload = overload
        if overload is not None:
            self._budget = overload.budget
            self.max_inflight = self._budget.limit
        else:
            self.max_inflight = (int(max_inflight)
                                 if max_inflight is not None
                                 else 2 * max_batch * workers)
            self._budget = InflightBudget(self.max_inflight,
                                          registry=self.registry)

        # -- shared scorer edge: one breaker, one coalescing batcher -------
        self._degrade = (degrade if degrade is not None
                         else (host_score_fn is not None
                               or breaker is not None))
        if self._degrade and breaker is None:
            from ccfd_tpu.router.router import default_scorer_breaker

            breaker = default_scorer_breaker(self.registry)
        self._breaker = breaker

        self.batcher = None
        worker_score: Any = score_fn
        # The fused decision plane bypasses the coalescing batcher the
        # same way history-aware scorers do: its decide() IS the device
        # dispatch (score + rules in one executable) and chunks on the
        # scorer's own bucket ladder — a row-concatenating batcher in
        # front would only re-split what decide re-buckets anyway, and
        # its proba-only wire cannot carry the fired-index column back.
        if (coalesce and workers > 1 and decision_fn is None
                and not callable(getattr(score_fn, "score_with_ids", None))):
            from ccfd_tpu.serving.batcher import DynamicBatcher

            c_disp = self.registry.counter(
                "router_coalesced_dispatches_total",
                "device dispatches made on behalf of the worker pool — "
                "fewer than router_worker_batches_total means concurrent "
                "workers' sub-batches coalesced",
            )
            c_rows = self.registry.counter(
                "router_coalesced_rows_total",
                "transaction rows scored through the coalescing batcher",
            )

            def on_dispatch(n_rows: int) -> None:
                c_disp.inc()
                c_rows.inc(n_rows)

            self.batcher = DynamicBatcher(
                score_fn,
                # one dispatch can absorb every worker's full poll; the
                # scorer's own shape bucketing pads it to a compiled size
                max_batch=(coalesce_max_batch
                           or max_batch * workers),
                deadline_ms=(cfg.batch_deadline_ms
                             if coalesce_deadline_ms is None
                             else coalesce_deadline_ms),
                on_dispatch=on_dispatch,
                workers=max(1, coalesce_workers),
            )
            worker_score = self.batcher.score

        self.workers = [
            Router(
                cfg, broker, worker_score, engine, self.registry,
                max_batch=max_batch, rules=rules,
                host_score_fn=host_score_fn, breaker=self._breaker,
                degrade=degrade, max_inflight=self.max_inflight,
                tracer=tracer, inflight_budget=self._budget, worker_id=i,
                overload=overload, profiler=profiler, heal_gate=heal_gate,
                # ONE shared decision-provenance log: every worker stamps
                # into the same ring/segments, so conservation (routed ==
                # recorded) holds across the pool, like the budget bound
                audit=audit,
                commit_after_route=commit_after_route,
                decision_fn=decision_fn,
            )
            for i in range(workers)
        ]
        self._c_in = self.registry.counter(
            "transaction_incoming_total", "transactions consumed")
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- facade ------------------------------------------------------------
    @property
    def engine(self) -> EngineClient:
        return self.workers[0].engine

    def step(self, poll_timeout_s: float = 0.0) -> int:
        """One synchronous cycle across every worker (tests/tools). Workers
        step sequentially on the calling thread; with the batcher on, each
        lone submit dispatches immediately (the batcher's lone-request
        fast path), so step() stays deterministic."""
        return sum(w.step(poll_timeout_s) for w in self.workers)

    # -- group-wide checkpoint barrier -------------------------------------
    def pause(self, timeout_s: float = 10.0) -> bool:
        """Group-wide batch-boundary hold: EVERY worker parked with its
        in-flight batch fully routed. Holds are requested on all workers
        up front, then acks awaited against one shared deadline — on True
        nothing consumed-but-unrouted exists anywhere in the pool (the
        shared batcher is necessarily idle: each worker waits out its own
        submission before acking), which is exactly the cut-consistency
        the checkpoint coordinator needs."""
        import time

        deadline = time.monotonic() + timeout_s
        for w in self.workers:
            w.request_pause()
        ok = True
        for w in self.workers:
            ok = w.await_pause(max(0.0, deadline - time.monotonic())) and ok
        return ok

    def resume(self) -> None:
        for w in self.workers:
            w.resume()

    def recycle_consumers(self) -> None:
        """Close and recreate every worker's consumers (crash recovery,
        with the group barrier held). Each recycle is a group rebalance;
        after the last one the pool holds a fresh disjoint assignment."""
        for w in self.workers:
            w.recycle_consumers()

    def set_heal_gate(self, gate: "Any | None") -> None:
        """Point every worker's degradation ladder at the device heal
        gate (runtime/heal.py) — the pool shares ONE DeviceSupervisor,
        like it shares one breaker and one budget."""
        for w in self.workers:
            w.set_heal_gate(gate)

    def swap_engine(self, engine: EngineClient) -> None:
        for w in self.workers:
            w.swap_engine(engine)

    # -- daemon loop (Supervisor-shaped: run blocks, stop unblocks) --------
    def reset(self) -> None:
        self._stop.clear()
        for w in self.workers:
            w.reset()

    def run(self, poll_timeout_s: float = 0.05, pipeline: bool = True) -> None:
        """Spawn one driver thread per worker and block until stop(). The
        supervisor treats this exactly like Router.run: the service body
        blocks, stop() unblocks it, reset() re-arms for the respawn.

        Crash visibility: a worker loop crash must not strand its
        partition subset behind a run() that still looks healthy — the
        first crash stops the WHOLE pool and re-raises out of run(), so
        the supervisor sees the failure and respawns the service exactly
        as it would for a crashed single Router."""
        crashes: list[BaseException] = []

        def worker_main(w: Router) -> None:
            try:
                # keyed on the POOL's stop flag: a driver that unwedges
                # long after a previous shutdown (its own Router._stop was
                # set back then) re-enters the loop instead of exiting,
                # so a reused zombie driver can never strand its worker
                while not self._stop.is_set():
                    w.reset()
                    w.run(poll_timeout_s, pipeline)
            # ccfd-lint: disable=counted-drops -- not a drop: the crash is collected and re-raised out of run() for the supervisor
            except BaseException as e:  # noqa: BLE001 - propagate via run()
                crashes.append(e)
                self.stop()

        # reuse still-alive drivers from a previous incarnation (a worker
        # wedged in a device score can outlive the last shutdown's bounded
        # join): spawning a SECOND driver for the same Router would race
        # its consumers and corrupt the shared budget accounting once the
        # zombie unwedges — the zombie itself resumes as the driver
        threads: list[threading.Thread] = []
        for i, w in enumerate(self.workers):
            old = self._threads[i] if i < len(self._threads) else None
            if old is not None and old.is_alive():
                threads.append(old)
                continue
            t = threading.Thread(
                target=worker_main, args=(w,),
                daemon=True, name=f"ccfd-router-w{i}",
            )
            threads.append(t)
            t.start()
        self._threads = threads
        self._stop.wait()
        for w in self.workers:
            w.stop()
        for t in threads:
            t.join(timeout=30)
        if crashes:
            raise crashes[0]

    def start(
        self, poll_timeout_s: float = 0.05, pipeline: bool = True
    ) -> threading.Thread:
        self.reset()
        t = threading.Thread(
            target=self.run, args=(poll_timeout_s, pipeline),
            daemon=True, name="ccfd-router",
        )
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()
        for w in self.workers:
            w.stop()

    def close(self) -> None:
        self.stop()
        for w in self.workers:
            w.close()
        if self.batcher is not None:
            self.batcher.stop()
