"""Stream decision router — the Camel/Fuse + Drools capability, TPU-batched.

The reference's ``ccd-fuse`` router consumes transactions from Kafka one
message at a time, POSTs each to Seldon, applies a Drools rule against
``FRAUD_THRESHOLD`` and starts a "fraud" or "standard" process on the KIE
server; it also forwards customer responses from the response topic as
process signals (reference deploy/router.yaml:54-70, README.md:424-459,
547-552, 567-569).

The TPU-native difference is the dispatch unit: **the Kafka poll IS the
micro-batch**. Each ``step()`` drains up to ``max_batch`` records within a
poll deadline, decodes them into one (B, 30) matrix, and makes a single
scorer dispatch — one XLA executable launch amortized over the whole batch —
instead of one HTTP round-trip per transaction. Threshold routing then runs
vectorized on the returned probability array.

Business counters match the reference metric names (README.md:522-530,
Router.json:88-326): ``transaction_incoming_total``,
``transaction_outgoing_total{type}``, ``notifications_outgoing_total``,
``notifications_incoming_total{response}``.

**Degradation ladder** (round 6; runtime/breaker.py): with ``degrade`` on
(implicit when a ``host_score_fn`` or ``breaker`` is supplied), a sick
scorer edge degrades scoring quality instead of stalling or dropping the
ingest loop — device scorer → host-tier numpy forward → rules-only
conservative scoring — with per-tier ``router_degraded_total{tier}``
counters, a circuit breaker on the scorer edge (an OPEN circuit skips the
device instantly, so a blackholed endpoint costs one bounded stall per
breaker window, not one per micro-batch), response validation (a corrupt
scorer reply — wrong shape, non-finite probabilities — counts as an edge
failure and falls down the ladder), and bounded in-flight load shedding
(``max_inflight`` records consumed-but-unrouted; oldest dropped first,
counted in ``router_shed_total``). Without the ladder the historical
semantics hold: a scorer failure drops that batch, counted.
"""

from __future__ import annotations

import contextlib
import logging
import operator
import threading
import time
from typing import Any, Callable, Mapping, Protocol

import numpy as np

from ccfd_tpu.bus.broker import Broker, StaleEpochError
from ccfd_tpu.config import Config
from ccfd_tpu.data.ccfd import FEATURE_NAMES
from ccfd_tpu.metrics.prom import Registry
from ccfd_tpu.native import decode_csv as native_decode_csv
from ccfd_tpu.process.fraud import CUSTOMER_RESPONSE_SIGNAL
from ccfd_tpu.router.rules import RuleSet, default_rules


class EngineClient(Protocol):
    """KIE-server-shaped surface the router needs (in-process or REST)."""

    def start_process(self, def_id: str, variables: Mapping[str, Any]) -> int: ...

    def signal(self, pid: int, name: str, payload: Any = None) -> bool: ...


_SCHEMA_GETTER = operator.itemgetter(*FEATURE_NAMES)
_ZERO_ROW = (0.0,) * len(FEATURE_NAMES)
_NULL_CM = contextlib.nullcontext()  # reusable: enter/exit hold no state


def default_scorer_breaker(registry):
    """The scorer-edge breaker the degradation ladder builds when none is
    supplied — ONE definition so the single Router and the ParallelRouter
    pool degrade on the same profile (an open circuit is what keeps a
    blackholed scorer from stalling every micro-batch)."""
    from ccfd_tpu.runtime.breaker import CircuitBreaker

    return CircuitBreaker(
        edge="scorer", registry=registry, min_calls=3,
        failure_ratio=0.5, cooldown_s=1.0,
    )


class InflightBudget:
    """Consumed-but-unrouted row budget, shareable across router workers.

    A single Router owns a private budget (the historical ``max_inflight``
    semantics). Under :class:`~ccfd_tpu.router.parallel.ParallelRouter`
    every worker shares ONE budget, so N workers cannot hold N× the
    configured bound — the bound is a statement about how much consumed
    work the process may have in flight, not about any one loop.

    ``reserve`` grants up to ``n`` rows and the caller sheds the rest;
    ``release`` returns rows once they are fully routed (or dropped).

    With a ``registry``, the current limit and utilization export as
    ``ccfd_inflight_limit`` / ``ccfd_inflight_used`` gauges labeled by
    ``stage`` — a fixed cap used to be invisible (you saw the sheds, not
    the bound), and the adaptive subclass
    (:class:`~ccfd_tpu.runtime.overload.AdaptiveInflightBudget`) MOVES the
    limit, which the Resilience/Overload boards chart.
    """

    __slots__ = ("limit", "_n", "_mu", "_g_limit", "_g_used", "_stage")

    def __init__(self, limit: int, registry=None, stage: str = "router"):
        self.limit = int(limit)
        self._n = 0
        self._mu = threading.Lock()
        self._stage = {"stage": stage}
        self._g_limit = self._g_used = None
        if registry is not None:
            self._g_limit = registry.gauge(
                "ccfd_inflight_limit",
                "in-flight row budget per stage (adaptive when the "
                "overload plane is armed)",
            )
            self._g_used = registry.gauge(
                "ccfd_inflight_used", "in-flight rows reserved per stage"
            )
            self._set_gauges_locked()

    def _set_gauges_locked(self) -> None:
        if self._g_limit is not None:
            self._g_limit.set(self.limit, labels=self._stage)
            self._g_used.set(self._n, labels=self._stage)

    def reserve(self, n: int) -> int:
        """Take up to ``n`` rows from the budget; returns rows granted."""
        with self._mu:
            take = min(n, max(0, self.limit - self._n))
            self._n += take
            self._set_gauges_locked()
            return take

    def try_reserve(self, n: int, ceiling: float = 1.0) -> bool:
        """All-or-nothing reserve (request-atomic admission): grant only
        when the post-grant utilization stays at or under ``ceiling``.
        An idle stage always grants — a lone request bigger than the
        (possibly adapted-down) limit must run alone, not starve."""
        with self._mu:
            if self._n == 0 or self._n + n <= int(self.limit * ceiling):
                self._n += n
                self._set_gauges_locked()
                return True
            return False

    def release(self, n: int) -> None:
        with self._mu:
            self._n = max(0, self._n - n)
            self._set_gauges_locked()

    def room(self) -> int:
        """Rows the budget could grant right now (backpressure probe)."""
        with self._mu:
            return max(0, self.limit - self._n)

    @property
    def inflight(self) -> int:
        return self._n


def _decode_row_lenient(tx: Any, out_row: np.ndarray) -> int:
    """Field-by-field decode for rows the fast path rejected; returns #bad."""
    if not (type(tx) is dict or isinstance(tx, Mapping)):
        return 1
    bad = 0
    for j, name in enumerate(FEATURE_NAMES):
        v = tx.get(name)
        if v is None:
            continue
        try:
            out_row[j] = float(v)
        except (TypeError, ValueError):
            bad += 1
    return bad


def decode_features(values: list[Mapping[str, Any]]) -> tuple[np.ndarray, int]:
    """Transaction dicts -> ((B, 30) float32 matrix in schema order, #bad fields).

    Hot path: well-formed transactions carry the full schema, so one
    ``itemgetter`` call per row pulls all 30 fields in C, and ONE
    ``np.asarray`` converts the whole batch — ~10x over per-field Python
    loops, which matters because this runs per micro-batch at wire rate
    (it was the single largest cost in the router loop profile).

    Malformed rows (missing fields, non-numeric values, non-mappings) fall
    back to the field-by-field lenient decode: they cost more but decode to
    0.0 per bad field instead of raising — a poison-pill message must not
    take down the scoring loop.
    """
    n = len(values)
    rows: list[tuple] = []
    slow: list[int] = []
    for i, tx in enumerate(values):
        try:
            rows.append(_SCHEMA_GETTER(tx))
        except (KeyError, TypeError):
            rows.append(_ZERO_ROW)
            slow.append(i)
    try:
        out = np.asarray(rows, np.float32)
        if out.shape != (n, len(FEATURE_NAMES)):
            raise ValueError("ragged rows")
    except (TypeError, ValueError):
        # some row carried an unparseable value: redo per row, diverting
        # failures to the lenient path
        out = np.zeros((n, len(FEATURE_NAMES)), np.float32)
        fast_ok = set(range(n)) - set(slow)
        slow = list(slow)
        for i in sorted(fast_ok):
            try:
                out[i] = np.asarray(rows[i], np.float32)
            except (TypeError, ValueError):
                slow.append(i)
    bad = 0
    for i in slow:
        out[i] = 0.0
        bad += _decode_row_lenient(values[i], out[i])
    return out, bad


def decode_records(records) -> tuple[np.ndarray, list[Mapping[str, Any]], int]:
    """Bus records -> ((B, 30) matrix, per-row tx dicts, #malformed fields).

    The one decoder for the transaction topic's mixed wire formats — the
    router's scoring batches and the drift monitor's windows must see the
    SAME rows. Two formats share the batch: dict transactions (decoded in
    Python) and raw CSV lines (decoded by the native C++ fast path in one
    pass). Rows keep their arrival order; a poison pill decodes to an
    all-zero row rather than crashing the loop.
    """
    n = len(records)
    x = np.zeros((n, len(FEATURE_NAMES)), np.float32)
    txs: list[Mapping[str, Any]] = [{}] * n
    bad = 0
    dict_rows: list[int] = []
    dict_vals: list[Mapping[str, Any]] = []
    csv_rows: list[int] = []
    csv_lines: list[bytes] = []
    # per-record dispatch loop: bound methods hoisted — this runs per
    # record at wire rate and its GIL-bound constant is part of the
    # parallel fan-out's scaling ceiling
    app_di, app_dv = dict_rows.append, dict_vals.append
    app_ci, app_cl = csv_rows.append, csv_lines.append
    for i, rec in enumerate(records):
        v = rec.value
        # exact-type checks first: typing/ABC __instancecheck__ costs ~1us
        # and this runs per record at wire rate — a CSV record must not
        # pay a failed Mapping protocol check before its cheap bytes test
        tv = type(v)
        if tv is dict:
            app_di(i)
            app_dv(v)
        elif tv is bytes or tv is str or isinstance(v, (bytes, str)):
            raw = v.encode() if isinstance(v, str) else v
            # one record == one CSV row; embedded newlines would desync
            # the joined decode below. The common case has none — a
            # memchr find beats allocating a splitlines list per record.
            if raw.find(b"\n") >= 0:
                lines = raw.splitlines() or [b""]
                bad += len(lines) - 1
                raw = lines[0]
            app_ci(i)
            app_cl(raw)
        elif isinstance(v, Mapping):  # non-dict mappings: same dict path
            app_di(i)
            app_dv(v)
        else:  # poison pill: score as all-zeros rather than crash the loop
            bad += 1
    if dict_vals:
        xd, bad_fields = decode_features(dict_vals)
        bad += bad_fields
        if len(dict_vals) == n:  # homogeneous batch: no row scatter needed
            x = xd
            txs = dict_vals
        else:
            x[dict_rows] = xd
            for j, i in enumerate(dict_rows):
                txs[i] = dict_vals[j]
    if csv_lines:
        xc, bad_csv = native_decode_csv(
            b"\n".join(csv_lines) + b"\n", len(FEATURE_NAMES)
        )
        bad += bad_csv
        amount_col = FEATURE_NAMES.index("Amount")
        if xc.shape[0] == n and len(csv_lines) == n:
            x = np.ascontiguousarray(xc, np.float32)
        else:
            for j, i in enumerate(csv_rows):
                if j < xc.shape[0]:
                    x[i] = xc[j]
        # one vectorized column read + tolist instead of a numpy-scalar
        # float() per row (~6x on this loop)
        amounts = x[:, amount_col][csv_rows].tolist() if len(
            csv_rows) != n else x[:, amount_col].tolist()
        for i, amt in zip(csv_rows, amounts):
            txs[i] = {"id": records[i].key, "Amount": amt}
    return x, txs, bad


class Router:
    def __init__(
        self,
        cfg: Config,
        broker: Broker,
        score_fn: Callable[[np.ndarray], np.ndarray],
        engine: EngineClient,
        registry: Registry | None = None,
        max_batch: int = 4096,
        rules: RuleSet | None = None,
        host_score_fn: Callable[[np.ndarray], np.ndarray] | None = None,
        breaker: "Any | None" = None,
        degrade: bool | None = None,
        max_inflight: int | None = None,
        tracer: "Any | None" = None,
        inflight_budget: InflightBudget | None = None,
        worker_id: int | None = None,
        overload: "Any | None" = None,
        profiler: "Any | None" = None,
        heal_gate: "Any | None" = None,
        audit: "Any | None" = None,
        commit_after_route: bool = False,
        decision_fn: "Any | None" = None,
    ):
        self.cfg = cfg
        self.broker = broker
        self.score = score_fn
        # observability/trace.py: per micro-batch, the router RESUMES the
        # trace context the producer stamped on the records ("router.batch"
        # span parented on the producer's span) and opens child spans for
        # decode/score/route — the per-stage latency attribution the
        # Tracing board and tools/trace_report.py decompose. Fraud-routed
        # and degraded-tier batches flag their spans, which the tail
        # sampler always keeps.
        self.tracer = tracer
        # history-aware scorers (serving/history.py SeqScorer) score each
        # transaction against the customer's history: they expose
        # score_with_ids(txs, x) and the router feeds them the decoded
        # records alongside the feature matrix; plain scorers get (x,)
        score_with_ids = getattr(score_fn, "score_with_ids", None)
        if callable(score_with_ids):
            self._score2 = lambda x, txs: (
                np.asarray(score_with_ids(txs, x)), None)
        else:
            self._score2 = lambda x, txs: (np.asarray(self.score(x)), None)
        self.engine = engine
        self.registry = registry or Registry()
        self.max_batch = max_batch
        # Drools-analog rule base (ccfd_tpu/router/rules.py). Precedence:
        # explicit arg > CCFD_RULES file > the reference's threshold rule.
        if rules is None:
            rules = (
                RuleSet.from_file(cfg.rules_file)
                if cfg.rules_file
                else default_rules(cfg.fraud_threshold)
            )
        self.rules = rules
        # Fused decision plane (serving/fused.py): one device dispatch
        # returns (proba, fired) — score, threshold and the vectorizable
        # rule base evaluated in ONE executable, so _route_inner skips the
        # host rules pass entirely. The decision fn REPLACES the score
        # seam (same tuple contract as _score2); its staged fallback
        # returns fired=None and the host rules pass resumes — the
        # degradation ladder below it (host forward, rules floor) is
        # untouched. Guard: the fused plan must have been compiled from
        # THIS router's rule base, or device-computed fired indices would
        # silently index a different rule table.
        if decision_fn is not None:
            dec_rules = getattr(decision_fn, "rules", None)
            if dec_rules is not None and dec_rules is not self.rules:
                logging.getLogger("ccfd_tpu.router").warning(
                    "decision_fn was compiled against a different RuleSet "
                    "than this router serves; fused decisions disarmed — "
                    "pass the same RuleSet instance to both")
                decision_fn = None
            else:
                dec = getattr(decision_fn, "decide", decision_fn)
                self._score2 = lambda x, txs: dec(x)
        self._decision_fn = decision_fn
        # Fail fast on a rule naming a process the engine doesn't have —
        # discovering it on the first matching transaction would kill the
        # routing loop mid-batch. Remote (REST) engines don't expose a
        # definition list; those fall back to the runtime guard in step().
        list_defs = getattr(engine, "definitions", None)
        if callable(list_defs):
            known = set(list_defs())
            missing = {r.process for r in rules.rules} - known
            if missing:
                raise ValueError(
                    f"rules reference unregistered processes {sorted(missing)}; "
                    f"engine has {sorted(known)}"
                )

        # engines (in-process or REST) exposing the batched start API get
        # one call per (rule, micro-batch) group instead of one per tx
        self._start_batch = getattr(engine, "start_process_batch", None)
        # in-process engines advertise copy_vars=False support (the
        # router's variables dicts are freshly built and never reused, so
        # the engine's defensive copy is pure overhead on the hot path);
        # the flag passes through method proxies where a signature
        # inspection would not
        self._start_nocopy = bool(getattr(engine, "start_batch_nocopy",
                                          False))

        # commit-after-route discipline (fleet plane, ISSUE 16): the tx
        # consumer runs manual-commit — a batch's offsets commit only
        # once every record has a terminal disposition (routed, shed, or
        # counted error). A member killed mid-batch leaves its offsets
        # UNcommitted, so the batch redelivers to whichever member
        # re-adopts the partitions (no drop); the bus's epoch fence
        # refuses the dead member's in-flight commit (no double-route
        # within an epoch). Off by default: the single-process platform
        # keeps the historical commit-on-poll hand-off.
        self._commit_after_route = bool(commit_after_route)
        # single source of truth for the consumer wiring: __init__ AND
        # recycle_consumers (crash recovery) both build from this.
        # manual=True marks the consumer that must be built
        # auto_commit=False when commit-after-route is armed.
        self._consumer_specs = (
            ("_tx_consumer", "router", (cfg.kafka_topic,), True),
            ("_resp_consumer", "router-responses",
             (cfg.customer_response_topic,), False),
            ("_notif_watcher", "router-notifications",
             (cfg.customer_notification_topic,), False),
        )
        for attr, group, topics, manual in self._consumer_specs:
            setattr(self, attr, self._build_consumer(group, topics, manual))

        r = self.registry
        self._c_in = r.counter("transaction_incoming_total", "transactions consumed")
        self._c_out = r.counter(
            "transaction_outgoing_total", "process starts by type"
        )
        self._c_notif_out = r.counter(
            "notifications_outgoing_total", "customer notifications observed"
        )
        self._c_notif_in = r.counter(
            "notifications_incoming_total", "customer responses by result"
        )
        self._h_batch = r.histogram("router_batch_size", "scoring batch sizes",
                                    buckets=(1, 8, 64, 256, 1024, 4096, 16384))
        self._c_decode_err = r.counter(
            "transaction_decode_errors_total", "malformed transaction fields"
        )
        self._h_score_s = r.histogram("router_score_seconds", "scorer dispatch latency")
        # the business SLO the reference's SeldonCore board tracks as
        # request quantiles (reference deploy/grafana/SeldonCore.json:499):
        # wall time from a record's PRODUCE timestamp to its process-start
        # decision — queueing + micro-batching + scoring + rules + engine
        self._h_decision_s = r.histogram(
            "router_decision_seconds",
            "producer->process-start decision latency",
        )
        self._c_rule = r.counter("router_rule_fired_total", "rule activations")
        self._c_start_err = r.counter(
            "router_process_start_errors_total", "failed process starts"
        )
        self._c_signal_err = r.counter(
            "router_signal_errors_total", "failed signal forwards"
        )
        self._c_score_err = r.counter(
            "router_score_errors_total",
            "scorer-edge failures: transactions dropped, or absorbed by "
            "degraded tiers when the ladder is on",
        )
        self._c_host_err = r.counter(
            "router_host_score_errors_total",
            "host-tier numpy-forward failures while the ladder was "
            "already degraded (the fall continues to the rules tier); "
            "its own family so the device-edge series stays label-uniform",
        )
        # -- degradation ladder (see module docstring) ---------------------
        self._host_score = host_score_fn
        self._degrade = (degrade if degrade is not None
                         else (host_score_fn is not None
                               or breaker is not None))
        self._breaker = breaker
        if self._degrade and breaker is None:
            self._breaker = default_scorer_breaker(r)
        self.max_inflight = (int(max_inflight) if max_inflight is not None
                             else 2 * max_batch)
        # overload-control plane (runtime/overload.py): adaptive AIMD
        # in-flight budget, deadline (CoDel) + priority-aware shedding,
        # and the dispatch watchdog. None keeps the historical static-
        # budget / oldest-first semantics. A ParallelRouter hands every
        # worker the SAME OverloadControl, so the adaptive bound — like
        # the static one — holds globally across the pool.
        self._overload = overload
        # the bounded-in-flight budget: private by default; a
        # ParallelRouter hands every worker the SAME budget so the bound
        # holds globally (satellite of the partition-parallel fan-out)
        if inflight_budget is not None:
            self._budget = inflight_budget
        elif overload is not None:
            self._budget = overload.budget
        else:
            self._budget = InflightBudget(self.max_inflight, registry=r)
        # device heal gate (runtime/heal.py DeviceSupervisor): while the
        # device is QUARANTINED (or on heal probation) the ladder is
        # PINNED to its host tier — the check sits ABOVE the breaker so
        # not even a half-open probe leaks live traffic to a sick device.
        # The supervisor itself canaries the device back to health.
        self._heal_gate = heal_gate
        # stage profiler (observability/profile.py): per micro-batch the
        # router feeds the decomposition no histogram carries — bus
        # queueing delay (poll time minus produce timestamps), decode and
        # route service time, and the scorer dispatch round trip, batch-
        # size-conditioned. None costs one attribute read per batch.
        self._profiler = profiler
        # decision provenance plane (observability/audit.py AuditLog):
        # when armed, the route seam stamps one compact DecisionRecord
        # per routed transaction — tx/uid/score/branch, the serving tier
        # that produced the score (threaded through a per-batch meta
        # dict so the pipelined loop's concurrent score/route stages
        # can't cross batches), admission priority, and the batch-
        # sampled lineage/incident joins. None costs one attribute read
        # per batch.
        self._audit = audit
        self._rec_pri = self._pri_names = None
        if audit is not None:
            # lazy: runtime/overload.py imports this module
            from ccfd_tpu.runtime.overload import (
                PRIORITY_NAMES,
                record_priority,
            )

            self._pri_names = PRIORITY_NAMES
            self._rec_pri = record_priority
        # worker identity (ParallelRouter): labels this loop's batches and
        # trace spans so per-stage attribution survives the fan-out
        self.worker_id = worker_id
        self._worker_labels = {"worker": str(worker_id or 0)}
        self._amount_idx = FEATURE_NAMES.index("Amount")
        self._c_degraded = r.counter(
            "router_degraded_total",
            "transactions scored by a degraded tier (host numpy forward "
            "or rules-only)",
        )
        self._c_shed = r.counter(
            "router_shed_total",
            "transactions dropped by bounded-in-flight load shedding "
            "(oldest first)",
        )
        self._c_fenced = r.counter(
            "router_fenced_commits_total",
            "post-route offset commits refused by the bus epoch fence "
            "(group rebalanced mid-batch): the batch redelivers to the "
            "partitions' new owners — an at-least-once duplicate, never "
            "a silent loss",
        )
        self._c_commit_err = r.counter(
            "router_commit_errors_total",
            "post-route offset commits lost to bus transport errors "
            "(not fences): the batch stays uncommitted and redelivers",
        )
        self._c_worker_batch = r.counter(
            "router_worker_batches_total",
            "scoring batches per router worker loop (worker 0 == the "
            "single-router case); compare against "
            "router_coalesced_dispatches_total to see fan-in",
        )
        self._stop = threading.Event()
        # checkpoint barrier (runtime/recovery.py): pause() parks the run
        # loop at a batch boundary — consumed records fully routed into the
        # engine, nothing in flight — so an engine snapshot plus the
        # committed offsets form a consistent cut (Flink-style aligned
        # checkpoint, scaled to one source)
        self._pause_req = threading.Event()
        self._pause_ack = threading.Event()
        # pause is reference-counted: the periodic checkpointer and an
        # operator drill (or crash restore) may hold the barrier at once,
        # and one holder's resume() must not release the other's hold
        self._pause_mu = threading.Lock()
        self._pause_holders = 0

    # -- commit-after-route (fleet plane) ----------------------------------
    def _build_consumer(self, group: str, topics: tuple, manual: bool):
        """Build one bus consumer; the tx consumer (``manual=True``) gets
        auto_commit=False when commit-after-route is armed. Brokers
        without the kwarg (older test doubles) fall back to auto-commit —
        and commit-after-route disarms itself, because the discipline is
        a lie over a consumer that commits on poll."""
        if not (manual and self._commit_after_route):
            return self.broker.consumer(group, topics)
        try:
            return self.broker.consumer(group, topics, auto_commit=False)
        except TypeError:
            self._commit_after_route = False
            return self.broker.consumer(group, topics)

    @staticmethod
    def _tx_offsets(records: list) -> dict[tuple[str, int], int] | None:
        """Commit positions for one poll's records: max offset + 1 per
        (topic, partition). Computed BEFORE admission — shed records are
        disposed (counted in router_shed_total) and must commit with the
        batch, or they would redeliver forever."""
        if not records:
            return None
        offs: dict[tuple[str, int], int] = {}
        for r in records:
            tp = (r.topic, r.partition)
            nxt = r.offset + 1
            if nxt > offs.get(tp, 0):
                offs[tp] = nxt
        return offs

    def _commit_routed(self, offs: dict | None) -> None:
        """Commit a fully-disposed batch's offsets (manual mode only).

        A fence (the group rebalanced since this batch was polled) is
        COUNTED and absorbed: the records redeliver to the partitions'
        current owners — the at-least-once outcome the fleet accounting
        tracks as cross-epoch redeliveries, never a loop crash. Transport
        errors likewise leave the batch uncommitted (it redelivers)."""
        if not self._commit_after_route or offs is None:
            return
        try:
            self._tx_consumer.commit(offs)
        except StaleEpochError:
            self._c_fenced.inc()
        except Exception:  # noqa: BLE001 - bus edge down; batch redelivers
            self._c_commit_err.inc()

    # -- loop stages (composed by step() and the pipelined run loop) -------
    def _drain_signals(self) -> None:
        """Notification-counter drain + customer-response signal forwarding."""
        for rec in self._notif_watcher.poll(self.max_batch, 0.0):
            self._c_notif_out.inc()

        for rec in self._resp_consumer.poll(self.max_batch, 0.0):
            payload = rec.value or {}
            approved = bool(payload.get("approved"))
            self._c_notif_in.inc(
                labels={"response": "approved" if approved else "non_approved"}
            )
            pid = payload.get("process_id")
            if pid is not None:
                try:
                    self.engine.signal(int(pid), CUSTOMER_RESPONSE_SIGNAL, payload)
                except Exception:
                    # remote engine briefly unreachable: the rest of the
                    # already-consumed response batch must still forward
                    self._c_signal_err.inc()

    def _poll_batch(self, poll_timeout_s: float) -> list:
        """Size x deadline micro-batching (SURVEY.md §7 stage 3): after the
        first records arrive, keep accumulating until the batch bucket
        fills or batch_deadline_ms elapses — under sustained load the TPU
        dispatch amortizes over a full bucket, while the deadline bounds
        the latency a lone transaction can be held for.

        With the overload plane armed the poll is budget-PREPAID: the
        loop reserves in-flight room BEFORE consuming and polls at most
        the grant, so a record is never consumed that cannot be admitted
        (consuming past capacity would force shedding records of EVERY
        priority — the inversion the plane exists to prevent). With no
        room the loop does not consume at all: backpressure propagates —
        the backlog stays in the bus, where the producer (and the Bus
        board) observe it as lag (``bus_topic_backlog``) instead of an
        unbounded consumed-then-shed churn. Polling resumes as routed
        batches release rows."""
        cap = self.max_batch
        granted = -1
        if self._overload is not None:
            granted = self._budget.reserve(self.max_batch)
            if granted <= 0:
                if poll_timeout_s > 0:
                    time.sleep(min(poll_timeout_s, 0.02))
                return []
            cap = granted
        records = self._tx_consumer.poll(cap, poll_timeout_s)
        if records:
            deadline_s = self.cfg.batch_deadline_ms / 1e3
            if deadline_s > 0 and len(records) < cap:
                deadline = time.perf_counter() + deadline_s
                while len(records) < cap:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    more = self._tx_consumer.poll(
                        cap - len(records), remaining
                    )
                    if not more:
                        break  # poll slept out the remaining deadline
                    records.extend(more)
        if granted >= 0 and granted > len(records):
            self._budget.release(granted - len(records))
        return records

    # -- tracing helpers ---------------------------------------------------
    def _begin_batch_span(self, records: list):
        """Open the micro-batch span, parented on the trace context the
        producer stamped onto the records (first stamped record wins — a
        batch mixes producer batches; per-stage attribution needs ONE
        parent and the stages are batch-granular anyway). Returns None
        when tracing is off."""
        if self.tracer is None:
            return None
        from ccfd_tpu.observability.trace import extract_context

        parent = None
        for rec in records[:16]:  # stamped records carry it up front
            h = getattr(rec, "headers", None)
            if h:
                parent = extract_context(h)
                if parent is not None:
                    break
        attrs: dict = {"records": len(records)}
        if self.worker_id is not None:
            attrs["worker"] = self.worker_id
        return self.tracer.start("router.batch", parent=parent, attrs=attrs)

    def _decode_batch(
        self, records: list, batch_span=None
    ) -> tuple[np.ndarray, list, np.ndarray]:
        n = len(records)
        self._c_in.inc(n)
        self._h_batch.observe(n)
        self._c_worker_batch.inc(labels=self._worker_labels)
        span_cm = (self.tracer.span("router.decode",
                                    parent=batch_span.context)
                   if batch_span is not None else None)
        t0 = time.perf_counter()
        with (span_cm if span_cm is not None else _NULL_CM):
            x, txs, bad = decode_records(records)
        if bad:
            self._c_decode_err.inc(bad)
        # produce timestamps ride along so _route can observe the
        # end-to-end decision latency (producer -> process start)
        ts = np.fromiter((r.timestamp for r in records), np.float64, n)
        if self._profiler is not None or batch_span is not None:
            # bus queueing delay: how long this batch's rows waited on the
            # topic before the poll (mean across the batch — the component
            # that sums with service/dispatch to the decision latency)
            # ccfd-lint: disable=monotonic-durations -- record timestamps are wall-clock by contract (cross-process); max(0,...) clamps an NTP step
            queue_s = max(0.0, time.time() - float(ts.mean()))
            if batch_span is not None:
                # ride the span too: the profiler's span-ingestion path
                # (and offline trace analysis) reads it from the attrs
                batch_span.attrs["queue_s"] = queue_s
            if self._profiler is not None:
                self._profiler.observe("bus", queue_s=queue_s, rows=n)
                self._profiler.observe(
                    "router.decode",
                    service_s=time.perf_counter() - t0, batch=n, rows=n)
        return x, txs, ts

    # -- decision provenance -----------------------------------------------
    def _audit_meta(self, records: list) -> dict | None:
        """Per-batch audit context, built while the bus records (the only
        carriers of partition/offset and priority headers) are still in
        scope. Rides WITH the batch through score and route — the
        pipelined loop scores batch k while routing k-1, so batch-scoped
        state must never live on ``self``."""
        if self._audit is None:
            return None
        names, pri = self._pri_names, self._rec_pri
        return {
            "uids": [f"{r.partition}:{r.offset}" for r in records],
            "pris": [names[pri(r)] for r in records],
            "events": [],
            "tier": "device",
            "cause": None,
        }

    # -- degradation ladder ------------------------------------------------
    def _shed_oldest(self, records: list) -> list:
        """Bounded in-flight: drop the OLDEST consumed records when a poll
        would push consumed-but-unrouted work past the budget. Under
        total saturation (every tier slow AND the bus backlogged) shedding
        the stalest work keeps decision latency bounded for what remains —
        the SRE load-shedding move. Shed records still count as incoming
        (they were consumed); ``router_shed_total`` records the drops.

        The budget is RESERVED here and released once the surviving rows
        are fully routed — with a shared budget (ParallelRouter) the bound
        therefore holds across every worker, not per loop."""
        granted = self._budget.reserve(len(records))
        if granted == len(records):
            return records
        shed = len(records) - granted
        self._c_in.inc(shed)
        self._c_shed.inc(shed)
        return records[shed:] if granted else []

    def _admit(self, records: list) -> list:
        """Admission for one poll's records. With the overload plane armed
        the decision is deadline- and priority-aware (stale rows drop from
        the front, budget victims are picked bulk-first/critical-last,
        runtime/overload.py); without it, the historical oldest-first
        bounded-in-flight shed. Either way the budget is reserved for
        exactly the survivors and ``router_shed_total`` counts the drops."""
        if self._overload is None:
            return self._shed_oldest(records)
        keep, shed = self._overload.admit(records, prepaid=True)
        if shed:
            self._c_in.inc(shed)  # shed records were still consumed
            self._c_shed.inc(shed)
        return keep

    def _rules_proba(self, x: np.ndarray) -> np.ndarray:
        """Rules-only tier: a conservative ``FRAUD_THRESHOLD`` stand-in
        with no model at all. High-amount transactions (the reference
        engine's own risk split, CCFD_LOW_AMOUNT) take proba exactly AT
        the threshold so the salience-ordered fraud rule fires — flagging
        for investigation is the conservative failure mode for a fraud
        system — and the rest score 0.0 (standard). Every transaction
        still gets a decision through the normal rule base."""
        thr = np.float32(self.cfg.fraud_threshold)
        risky = x[:, self._amount_idx] >= self.cfg.low_amount_threshold
        return np.where(risky, thr, np.float32(0.0)).astype(np.float32)

    def _score_tiered(self, x: np.ndarray, txs: list,
                      span=None, meta=None) -> tuple:
        """device scorer → host numpy forward → rules-only. Never raises:
        the bottom tier is pure numpy over data already in hand. ``span``
        (when tracing) gets the degraded-tier flag — a trace scored by a
        fallback tier is always tail-sampled KEEP. ``meta`` (when the
        audit plane is armed) records the tier that actually produced
        the batch's scores and why the ladder fell.

        Returns ``(proba, fired)``: ``fired`` is the device-computed rule
        index vector when the fused decision plane produced this batch's
        verdicts, else None (host rules pass runs in ``_route_inner``).
        Fallback tiers always return fired=None — a degraded score must
        re-enter the full host rule base, never a stale device verdict."""
        gate = self._heal_gate
        host_blocked = False
        if gate is not None and not gate.device_allowed():
            # device quarantined (runtime/heal.py): the ladder is pinned
            # to the host tier. Checked BEFORE the breaker so a HALF_OPEN
            # probe slot cannot route live rows to the sick device — the
            # heal supervisor's own canary is the only probe allowed.
            if span is not None:
                span.attrs["quarantined"] = True
            # storage pin (runtime/durability.StoragePinGate): when NO
            # params generation verifies, the host tier would forward the
            # very same unverified tree — the ladder pins all the way to
            # the rules floor until a verified tree is published
            host_ok = getattr(gate, "host_allowed", None)
            host_blocked = callable(host_ok) and not host_ok()
            if meta is not None:
                meta["cause"] = ("storage_pin" if host_blocked
                                 else "quarantine")
        elif self._breaker is None or self._breaker.allow():
            br = self._breaker
            t0 = time.perf_counter()
            try:
                ov = self._overload
                if ov is not None and ov.dispatch_deadline_s > 0:
                    # dispatch watchdog: a hung/slow device dispatch (the
                    # seq path measured 1412 ms, BENCH_r05) is killed at
                    # the deadline and lands in this except — one breaker
                    # failure and a ladder fall, not a stalled worker
                    proba, fired = ov.bounded_dispatch(
                        lambda: self._score2(x, txs))
                else:
                    proba, fired = self._score2(x, txs)
                lat = time.perf_counter() - t0
                # corrupt-response validation: a fault-injected (or truly
                # version-skewed) reply with the wrong shape or non-finite
                # values must degrade, not route garbage decisions
                if proba.shape != (len(txs),) or not np.isfinite(proba).all():
                    raise ValueError("invalid scorer response")
                # fused verdicts get the same treatment: an index vector
                # of the wrong shape or out of the rule table's range
                # must degrade this batch, not mis-route it
                if fired is not None and (
                        getattr(fired, "shape", None) != (len(txs),)
                        or int(fired.min()) < 0
                        or int(fired.max()) >= len(self.rules.rules)):
                    raise ValueError("invalid scorer response")
                if br is not None:
                    br.record_success(lat)
                return proba, fired
            except Exception as e:
                if br is not None:
                    br.record_failure(time.perf_counter() - t0)
                self._c_score_err.inc(len(txs))
                if meta is not None:
                    # a watchdog kill is its own event class: the record
                    # must say "this decision fell to a fallback tier
                    # because the device dispatch was killed", not just
                    # "an error happened"
                    ev = ("watchdog_timeout"
                          if type(e).__name__ == "ScorerTimeout"
                          else "score_error")
                    meta["events"].append(ev)
                    meta["cause"] = meta["cause"] or ev
        elif span is not None or meta is not None:
            if span is not None:
                span.attrs["breaker_open"] = True
            if meta is not None:
                meta["events"].append("breaker_open")
                meta["cause"] = meta["cause"] or "breaker_open"
        if self._host_score is not None and not host_blocked:
            try:
                proba = np.asarray(self._host_score(x), np.float32)
                if proba.shape == (len(txs),) and np.isfinite(proba).all():
                    self._c_degraded.inc(len(txs), labels={"tier": "host"})
                    if span is not None:
                        span.attrs["degraded"] = "host"
                    if meta is not None:
                        meta["tier"] = "host"
                    return proba, None
            except Exception:  # noqa: BLE001 - fall to the rules tier
                # a host-forward failure was invisible before: the ladder
                # fell straight through and only the rules-tier counter
                # moved, so "host tier is broken" never had its own signal
                self._c_host_err.inc(len(txs))
        self._c_degraded.inc(len(txs), labels={"tier": "rules"})
        if span is not None:
            span.attrs["degraded"] = "rules"
        if meta is not None:
            meta["tier"] = "rules"
        return self._rules_proba(x), None

    def _score_direct(self, x: np.ndarray, txs: list,
                      span=None, meta=None) -> tuple:
        """Legacy non-ladder path — but the heal gate still binds: a
        quarantined device must not see live rows even when the
        degradation ladder is off (``router.degrade: false`` CRs). With
        no host tier wired here, the always-available rules tier makes
        the conservative decision until the supervisor re-promotes."""
        gate = self._heal_gate
        if gate is not None and not gate.device_allowed():
            if span is not None:
                span.attrs["quarantined"] = True
                span.attrs["degraded"] = "rules"
            if meta is not None:
                meta["tier"] = "rules"
                meta["cause"] = "quarantine"
            self._c_degraded.inc(len(txs), labels={"tier": "rules"})
            return self._rules_proba(x), None
        return self._score2(x, txs)

    def _score_batch(self, x: np.ndarray, txs: list,
                     batch_span=None, meta=None) -> tuple:
        if self.tracer is not None and batch_span is not None:
            with self.tracer.span("router.score",
                                  parent=batch_span.context) as sp:
                if self._degrade:
                    return self._score_tiered(x, txs, span=sp, meta=meta)
                return self._score_direct(x, txs, span=sp, meta=meta)
        if self._degrade:
            return self._score_tiered(x, txs, meta=meta)
        return self._score_direct(x, txs, meta=meta)

    # -- one synchronous cycle (used by tests and the run loop) ------------
    def step(self, poll_timeout_s: float = 0.0) -> int:
        """Route one poll's worth of work; returns #transactions scored."""
        self._drain_signals()
        records = self._poll_batch(poll_timeout_s)
        if not records:
            return 0
        offs = self._tx_offsets(records)
        records = self._admit(records)
        if not records:
            # fully shed: every record is disposed (counted), the batch
            # is complete — commit it
            self._commit_routed(offs)
            return 0
        batch_sp = None
        meta = self._audit_meta(records)
        try:
            batch_sp = self._begin_batch_span(records)
            x, txs, ts = self._decode_batch(records, batch_sp)
            t0 = time.perf_counter()
            proba, fired = self._score_batch(x, txs, batch_sp, meta)
            score_s = time.perf_counter() - t0
            self._h_score_s.observe(
                score_s,
                exemplar=({"trace_id": batch_sp.trace_id}
                          if batch_sp is not None else None))
            if self._overload is not None:
                # AIMD feedback: the scorer stage's measured latency vs its
                # budget is what moves the adaptive in-flight limit
                self._overload.observe_stage(score_s)
            if self._profiler is not None:
                self._profiler.observe("router.score", dispatch_s=score_s,
                                       batch=len(txs), rows=len(txs))
            n = self._route(x, txs, proba, ts, batch_span=batch_sp,
                            meta=meta, fired=fired)
            # commit ONLY after every record has a terminal disposition
            # (routed/shed/errored); a crash above leaves the batch
            # uncommitted, so it redelivers instead of vanishing
            self._commit_routed(offs)
            return n
        except BaseException:
            # a crashed batch is exactly the trace an operator needs:
            # error status forces the tail sampler's keep
            if batch_sp is not None:
                batch_sp.status = "error"
            raise
        finally:
            self._budget.release(len(records))
            if batch_sp is not None:
                self.tracer.finish(batch_sp)

    def _route(self, x: np.ndarray, txs: list, proba: np.ndarray,
               ts: np.ndarray | None = None, batch_span=None,
               meta=None, fired: np.ndarray | None = None) -> int:
        route_sp = None
        if self.tracer is not None and batch_span is not None:
            route_sp = self.tracer.start("router.route",
                                         parent=batch_span.context)
        t0 = time.perf_counter() if self._profiler is not None else 0.0
        try:
            if route_sp is None:
                return self._route_inner(x, txs, proba, ts, batch_span,
                                         route_sp, meta, fired)
            # activate on THIS thread: the engine calls below (and the
            # notification records the engine produces inside them,
            # process/fraud.py notify) read current_context() to join the
            # trace — an unactivated span would orphan the engine/notify leg
            with self.tracer.activate(route_sp.context):
                return self._route_inner(x, txs, proba, ts, batch_span,
                                         route_sp, meta, fired)
        finally:
            if self._profiler is not None:
                self._profiler.observe(
                    "router.route", service_s=time.perf_counter() - t0,
                    batch=len(txs), rows=len(txs))
            if route_sp is not None:
                self.tracer.finish(route_sp)

    def _route_inner(self, x: np.ndarray, txs: list, proba: np.ndarray,
                     ts: np.ndarray | None, batch_span, route_sp,
                     meta=None, fired: np.ndarray | None = None) -> int:
        if fired is None:
            fired = self.rules.evaluate(x, proba)
        # group the micro-batch by fired rule: one batched process-start per
        # (rule, process) instead of one engine round-trip per transaction —
        # the engine amortizes its lock (and the remote client its HTTP hop)
        # over the group, which is what lets L5 absorb the TPU scorer's
        # output rate (VERDICT r1: engine throughput >= scorer throughput).
        # tolist() first: iterating numpy arrays yields numpy scalars whose
        # per-element unboxing (and float() calls) dominated this loop's
        # profile — one C-speed conversion, then plain-Python iteration.
        # This loop is GIL-bound and runs once per worker batch, so its
        # constant factor IS the parallel fan-out's scaling ceiling.
        groups: dict[int, list[dict]] = {}
        rules = self.rules.rules
        plist = proba.tolist()
        # audit plane armed: track each group's original row indices so a
        # successful start stamps THAT row's tx/uid/priority/timestamp —
        # and only successful starts (conservation: routed == recorded;
        # a failed start is counted in router_process_start_errors_total,
        # not in the provenance stream)
        gidx: dict[int, list[int]] | None = \
            {} if (self._audit is not None and meta is not None) else None
        audit_rows: list[dict] = []
        ts_list = (ts.tolist()
                   if gidx is not None and ts is not None else None)
        # replay plane armed: embed the DECODED feature row per record so
        # audit segments alone reconstruct a re-scorable window (one
        # C-speed tolist outside the loop; off = zero cost)
        x_list = (x.tolist()
                  if gidx is not None
                  and getattr(self._audit, "capture_rows", False) else None)
        for i, (tx, p, ridx) in enumerate(zip(txs, plist, fired.tolist())):
            variables = {
                "transaction": tx,
                "proba": p,
                "customer_id": tx.get("id"),
            }
            set_vars = rules[ridx].set_vars
            if set_vars:
                variables.update(set_vars)
            g = groups.get(ridx)
            if g is None:
                groups[ridx] = [variables]
            else:
                g.append(variables)
            if gidx is not None:
                gi = gidx.get(ridx)
                if gi is None:
                    gidx[ridx] = [i]
                else:
                    gi.append(i)
        for ridx, vars_list in groups.items():
            rule = self.rules.rules[ridx]
            try:
                if self._start_batch is not None:
                    pids = (self._start_batch(rule.process, vars_list,
                                              copy_vars=False)
                            if self._start_nocopy
                            else self._start_batch(rule.process, vars_list))
                else:  # engine without the batch API: per-item, isolated
                    pids = []
                    for variables in vars_list:
                        try:
                            pids.append(
                                self.engine.start_process(rule.process, variables)
                            )
                        # ccfd-lint: disable=counted-drops -- the None sentinel is counted below (n_err -> router_process_start_errors_total)
                        except Exception:
                            pids.append(None)
            except Exception:
                # bad rule target or unreachable remote engine: the whole
                # group failed to start, but the routing loop (and the other
                # groups in this poll) must keep going
                self._c_start_err.inc(len(vars_list), labels={"type": rule.process})
                continue
            n_err = sum(1 for p in pids if p is None)
            if n_err:
                self._c_start_err.inc(n_err, labels={"type": rule.process})
            n_ok = len(pids) - n_err
            if n_ok:
                self._c_out.inc(n_ok, labels={"type": rule.process})
                self._c_rule.inc(n_ok, labels={"rule": rule.name})
                if route_sp is not None and "fraud" in rule.process:
                    # fraud-routed batches are always tail-sampled KEEP
                    route_sp.attrs["fraud"] = True
                if gidx is not None:
                    idx_list = gidx[ridx]
                    for j, pid in enumerate(pids):
                        if pid is None:
                            continue
                        i = idx_list[j]
                        row = {
                            "tx": txs[i].get("id"),
                            "uid": meta["uids"][i],
                            "ts": ts_list[i] if ts_list is not None else None,
                            "proba": plist[i],
                            "rule": rule.name,
                            "branch": rule.process,
                            "pid": pid,
                            "priority": meta["pris"][i],
                        }
                        # a replayed transaction carries its origin marker
                        # through the decode seam; stamping it onto the
                        # record lets the ReplayVerdictTap divert the
                        # verdict to the parity join instead of the
                        # provenance log
                        mk = txs[i].get("_replay")
                        if mk is not None:
                            row["replay"] = mk
                        if x_list is not None:
                            row["row"] = x_list[i]
                        audit_rows.append(row)
        if audit_rows:
            self._audit.record_batch(
                audit_rows,
                tier=meta.get("tier", "device"),
                cause=meta.get("cause"),
                events=tuple(meta.get("events", ())),
                worker=self.worker_id,
                trace_id=(batch_span.trace_id
                          if batch_span is not None else None),
                threshold=self.cfg.fraud_threshold,
            )
        if ts is not None and len(ts):
            # ccfd-lint: disable=monotonic-durations -- produce stamps are wall-clock record timestamps (cross-process decision latency)
            self._h_decision_s.observe_many(time.time() - ts)
        return len(txs)

    # -- checkpoint barrier ------------------------------------------------
    def pause(self, timeout_s: float = 10.0) -> bool:
        """Request a batch-boundary hold and wait for the loop to ack.

        On True, the loop is parked with every consumed record fully routed
        (in-flight scoring batch finished and started into the engine) and
        will stay parked until :meth:`resume` — the window in which an
        engine snapshot + committed offsets are a consistent cut. Returns
        False if no ack arrived (router stopped/crashed/not running); the
        caller decides whether proceeding is safe (a dead router isn't
        mutating engine state either).

        Holds nest: every pause() needs a matching resume(); the loop
        stays parked until the last holder releases."""
        self.request_pause()
        return self.await_pause(timeout_s)

    def request_pause(self) -> None:
        """Take a pause hold and signal the loop, WITHOUT waiting for the
        ack. The group-wide barrier (ParallelRouter) requests every
        worker's hold first, then awaits all acks — requesting
        sequentially with per-worker waits would let later workers keep
        consuming while earlier ones park, and the combined wait could
        take N× the timeout."""
        with self._pause_mu:
            self._pause_holders += 1
            self._pause_req.set()

    def await_pause(self, timeout_s: float) -> bool:
        """Wait for a previously requested pause to be acked."""
        return self._pause_ack.wait(timeout=timeout_s)

    def resume(self) -> None:
        with self._pause_mu:
            if self._pause_holders > 0:
                self._pause_holders -= 1
            if self._pause_holders == 0:
                self._pause_req.clear()

    def _pause_point(self) -> None:
        """Called by the run loops at a batch boundary."""
        self._pause_ack.set()
        while self._pause_req.is_set() and not self._stop.is_set():
            time.sleep(0.005)
        self._pause_ack.clear()

    def recycle_consumers(self) -> None:
        """Close and recreate the bus consumers — with the loop parked at
        the pause barrier (or stopped). Crash recovery calls this before
        rewinding group offsets: a parked loop still leaves the old
        consumers as LIVE group members on a real Kafka cluster
        (kafka-python heartbeats run on a background thread), and Kafka
        refuses offset resets for a non-empty group. In-process the same
        sequence is a cheap rebalance. The recreated consumers resume at
        the (about-to-be-rewound) committed offsets, like any group
        member."""
        for attr, group, topics, manual in self._consumer_specs:
            try:
                getattr(self, attr).close()
            except Exception:  # noqa: BLE001 - a dead consumer is fine here
                logging.getLogger("ccfd_tpu.router").debug(
                    "stale consumer %s failed to close during recycle",
                    attr, exc_info=True)
            setattr(self, attr, self._build_consumer(group, topics, manual))

    def set_heal_gate(self, gate: Any) -> None:
        """Arm (or, with None, disarm) the device heal gate after
        construction — the operator builds the DeviceSupervisor after the
        router (it needs the flight recorder from a later bring-up step)
        and points the ladder at it here. One attribute publish; the next
        batch sees it."""
        self._heal_gate = gate

    def swap_engine(self, engine: EngineClient) -> None:
        """Point the router at a replacement engine — crash recovery swaps
        in a snapshot-restored instance (runtime/recovery.py). The router
        must be paused or stopped. Re-validates rule targets and rebinds
        the cached batched-start path."""
        list_defs = getattr(engine, "definitions", None)
        if callable(list_defs):
            missing = {r.process for r in self.rules.rules} - set(list_defs())
            if missing:
                raise ValueError(
                    f"replacement engine lacks processes {sorted(missing)}"
                )
        self.engine = engine
        self._start_batch = getattr(engine, "start_process_batch", None)
        self._start_nocopy = bool(getattr(engine, "start_batch_nocopy",
                                          False))

    # -- daemon loop -------------------------------------------------------
    def reset(self) -> None:
        """Re-arm after stop() so the next run() actually loops. Called by
        the supervisor before each respawn (NOT inside run(): clearing on
        the service thread would race a concurrent stop() and erase it)."""
        self._stop.clear()

    def run(self, poll_timeout_s: float = 0.05, pipeline: bool = True) -> None:
        if pipeline:
            self._run_pipelined(poll_timeout_s)
        else:
            while not self._stop.is_set():
                if self._pause_req.is_set():
                    self._pause_point()
                    continue
                self.step(poll_timeout_s)

    def _run_pipelined(self, poll_timeout_s: float) -> None:
        """Overlap the device dispatch with everything else.

        ``step`` blocks the loop for the full scorer round trip — tens of
        ms through a tunneled TPU — during which no polling, rule eval, or
        process starts happen. Here batch k's dispatch runs on a dedicated
        thread (XLA releases the GIL for the device wait) while the loop
        routes batch k-1's results into the engine and polls batch k+1:
        the device and the Python/engine work pipeline instead of taking
        turns. One stage in flight is enough — depth beyond 1 only adds
        queueing latency because the loop itself is busy between waits.
        """
        from concurrent.futures import ThreadPoolExecutor

        def timed_score(x: np.ndarray, txs: list, batch_sp,
                        meta) -> tuple:
            # time INSIDE the worker so the histogram records the scorer
            # round trip, not dispatch + however long the loop polled.
            # batch_sp (and the audit meta) ride along explicitly — the
            # worker thread has no ambient trace context (contextvars are
            # per-thread), and batch-scoped audit state must never live
            # on self while two batches are in flight
            t0 = time.perf_counter()
            proba, fired = self._score_batch(x, txs, batch_sp, meta)
            score_s = time.perf_counter() - t0
            self._h_score_s.observe(
                score_s,
                exemplar=({"trace_id": batch_sp.trace_id}
                          if batch_sp is not None else None))
            if self._overload is not None:
                self._overload.observe_stage(score_s)
            if self._profiler is not None:
                self._profiler.observe("router.score", dispatch_s=score_s,
                                       batch=len(txs), rows=len(txs))
            return proba, fired

        def finish(pending: tuple) -> None:
            pfut, px, ptxs, pts, psp, pmeta, poffs = pending
            try:
                try:
                    proba, fired = pfut.result()
                except Exception:
                    # a transient scorer failure (e.g. remote model timeout)
                    # drops this batch, not the routing loop. The drop IS
                    # a terminal disposition (counted in
                    # router_score_errors_total), so the batch commits —
                    # redelivering it would double-count the error
                    self._c_score_err.inc(len(ptxs))
                    if psp is not None:
                        psp.status = "error"
                    self._commit_routed(poffs)
                    return
                self._route(px, ptxs, proba, pts, batch_span=psp,
                            meta=pmeta, fired=fired)
                self._commit_routed(poffs)
            except BaseException:
                if psp is not None:  # _route crashed: force-keep the trace
                    psp.status = "error"
                raise
            finally:
                self._budget.release(len(ptxs))
                if psp is not None:
                    self.tracer.finish(psp)

        ex = ThreadPoolExecutor(1, thread_name_prefix="ccfd-router-score")
        pending: tuple | None = None  # (future, x, txs, ts, batch_span)
        try:
            while not self._stop.is_set():
                if self._pause_req.is_set():
                    # finish the in-flight batch BEFORE acking: the ack
                    # promises nothing consumed-but-unrouted exists.
                    # (swap-then-finish everywhere in this loop: if
                    # finish raises, the batch must NOT still be pending —
                    # the outer finally would finish it a second time,
                    # double-routing its groups into the engine and
                    # double-releasing its rows from the SHARED budget)
                    if pending is not None:
                        done, pending = pending, None
                        finish(done)
                    self._pause_point()
                    continue
                self._drain_signals()
                # with a batch in flight, don't sleep on an empty topic:
                # grab whatever is already queued and route the in-flight
                # result promptly — a lone transaction's end-to-end latency
                # stays ~one scorer round trip instead of round trip +
                # poll_timeout (sparse-traffic p99)
                records = self._poll_batch(
                    0.0 if pending is not None else poll_timeout_s
                )
                offs = self._tx_offsets(records)
                if records:
                    # bounded in-flight: batch k-1's rows are still
                    # reserved (consumed-but-unrouted) while k is being
                    # submitted — the budget reserve inside _admit
                    # accounts for them (and, under ParallelRouter, for
                    # every other worker's in-flight rows too)
                    records = self._admit(records)
                    if not records:
                        # fully shed: disposed (counted) — commit now
                        self._commit_routed(offs)
                fut = None
                if records:
                    batch_sp = None
                    meta = self._audit_meta(records)
                    try:
                        batch_sp = self._begin_batch_span(records)
                        x, txs, ts = self._decode_batch(records, batch_sp)
                        fut = ex.submit(timed_score, x, txs, batch_sp, meta)
                    except BaseException:
                        # reserved rows must not leak out of a crashed
                        # loop (with a SHARED budget the leak would
                        # throttle every other worker forever), and the
                        # crashed batch's span is exactly the post-mortem
                        # trace the tail sampler must keep
                        self._budget.release(len(records))
                        if batch_sp is not None:
                            batch_sp.status = "error"
                            self.tracer.finish(batch_sp)
                        raise
                done, pending = pending, (
                    (fut, x, txs, ts, batch_sp, meta, offs)
                    if fut is not None else None)
                if done is not None:
                    try:
                        finish(done)
                    except BaseException:
                        # the loop is going down and the batch just
                        # submitted can never be routed: release its rows
                        # (shared-budget leak-proofing), count it as
                        # dropped, and keep its trace
                        if pending is not None:
                            _, _, ptxs, _, psp, _pm, _po = pending
                            pending = None
                            self._budget.release(len(ptxs))
                            self._c_score_err.inc(len(ptxs))
                            if psp is not None:
                                psp.status = "error"
                                self.tracer.finish(psp)
                        raise
        finally:
            try:
                if pending is not None:
                    finish(pending)
            finally:
                ex.shutdown()

    def start(
        self, poll_timeout_s: float = 0.05, pipeline: bool = True
    ) -> threading.Thread:
        # direct (unsupervised) start: re-arm here, before the thread exists
        self.reset()
        t = threading.Thread(
            target=self.run, args=(poll_timeout_s, pipeline),
            daemon=True, name="ccfd-router",
        )
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()

    def close(self) -> None:
        self.stop()
        self._tx_consumer.close()
        self._resp_consumer.close()
        self._notif_watcher.close()
