"""End-to-end overload control: adaptive admission + priority-aware shedding.

PR 1's breakers and degradation ladder defend every RPC edge against
*faults*; this module defends the pipeline against *overload* — the flash
crowd at the REST front, the partition-skewed hot key, the scorer whose
latency quietly doubled. The design follows the serving-robustness
literature the ROADMAP names: per-stage admission control with an
SLO-derived concurrency limit (InferLine, arXiv:1812.01776), overload
isolation as the defining serving problem at millions-of-users scale
("Scaling TensorFlow to 300M predictions/sec", arXiv:2109.09541), and the
SRE load-shedding canon (shed by value, never by arrival order alone).

Four pieces, composed by the router, the serving fronts and the operator:

- :class:`AdaptiveInflightBudget` — an AIMD concurrency limiter with the
  :class:`~ccfd_tpu.router.router.InflightBudget` surface, so it drops in
  wherever the static budget lived (one instance shared across every
  ParallelRouter worker keeps the PR-3 global-bound semantics). Each
  ``observe(latency)`` compares a stage's measured latency against its
  budget: over budget → multiplicative decrease (cooldown-limited so one
  burst can't collapse the limit), a window of in-budget observations →
  additive increase. The limit and its utilization export as
  ``ccfd_inflight_limit`` / ``ccfd_inflight_used`` gauges (labeled by
  stage) so the Resilience and Overload boards show the limit moving.
- :class:`DeadlinePolicy` — a CoDel-style deadline-aware queue policy:
  work is dropped FROM THE FRONT when its queue sojourn exceeds a target,
  so stale work never reaches the device (serving it would blow the SLO
  for everything behind it, the bufferbloat failure CoDel exists to kill).
  Targets scale per priority class — bulk work goes stale at 1× the
  target, normal at 2×, critical at 4× — which is what makes deadline
  shedding priority-ordered under a growing backlog.
- :class:`OverloadControl` — the router/bus-side admission plane (one per
  router pool; workers share it): deadline shedding + budget-bounded
  admission with priority-aware victim selection (bulk shed first,
  critical last, oldest-first within a class), a self-checking
  ``ccfd_priority_inversions_total`` tripwire, and the dispatch watchdog —
  a bounded device-dispatch call whose expiry trips the scorer-edge
  breaker instead of stalling a worker forever
  (``ccfd_dispatch_timeout_total``).
- :class:`AdmissionGate` — the serving-side (REST) admission plane:
  request-atomic reserve against an adaptive serving budget with
  priority-tiered utilization ceilings (bulk refused at 50% utilization,
  normal at 90%, critical at 100%), mapped by the fronts to an explicit
  429 + retry-after.

Priority classes ride as data: bus records carry a ``priority`` header
(``bulk`` / ``normal`` / ``critical``; the producer stamps per-chunk),
REST requests an ``x-ccfd-priority`` header. Fraud-suspect re-scores and
canary/shadow-evaluation traffic are stamped ``critical`` (shed LAST);
bulk re-score jobs ``bulk`` (shed FIRST); everything else defaults
``normal``.

Replay safety: deadline (CoDel) shedding on the bus judges records by
their PRODUCE timestamp, and crash recovery legitimately re-drives
minutes-old records — the bus deadline therefore defaults OFF
(``CCFD_OVERLOAD_CODEL_TARGET_MS=0``) and is armed explicitly for live
traffic; the adaptive budget and priority shedding are always safe and
default on under the operator.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Mapping

from ccfd_tpu.router.router import InflightBudget

# priority classes: "bigger is more precious" — shed ascending
PRIORITY_BULK, PRIORITY_NORMAL, PRIORITY_CRITICAL = 0, 1, 2
PRIORITY_NAMES = {PRIORITY_BULK: "bulk", PRIORITY_NORMAL: "normal",
                  PRIORITY_CRITICAL: "critical"}
_PRIORITY_BY_NAME = {
    "bulk": PRIORITY_BULK, "low": PRIORITY_BULK,
    "normal": PRIORITY_NORMAL, "default": PRIORITY_NORMAL,
    "critical": PRIORITY_CRITICAL, "high": PRIORITY_CRITICAL,
    # semantic aliases for the traffic the ISSUE pins to each end:
    "fraud": PRIORITY_CRITICAL, "canary": PRIORITY_CRITICAL,
    "shadow": PRIORITY_CRITICAL, "rescore": PRIORITY_BULK,
}


def parse_priority(value: Any, default: int = PRIORITY_NORMAL) -> int:
    """Header/payload value -> priority class. Accepts the class names
    (and their aliases), bytes, and bare ints; anything unparseable is
    NORMAL — a malformed header must not be a shed-first footgun."""
    if value is None:
        return default
    if isinstance(value, bytes):
        value = value.decode("latin-1", "replace")
    if isinstance(value, str):
        v = value.strip().lower()
        if v in _PRIORITY_BY_NAME:
            return _PRIORITY_BY_NAME[v]
        try:
            value = int(v)
        except ValueError:
            return default
    if isinstance(value, (int, float)):
        return min(PRIORITY_CRITICAL, max(PRIORITY_BULK, int(value)))
    return default


def headers_priority(headers: Any, default: int = PRIORITY_NORMAL) -> int:
    """Priority from a record/request header carrier: a mapping or a
    Kafka-style ``[(key, value), ...]`` list. Missing/None -> default."""
    if not headers:
        return default
    if isinstance(headers, Mapping):
        return parse_priority(headers.get("priority"), default)
    try:  # list of (key, value) pairs (bus/kafka_adapter header mapping)
        for k, v in headers:
            kk = k.decode("latin-1") if isinstance(k, bytes) else k
            if kk == "priority":
                return parse_priority(v, default)
    except (TypeError, ValueError):
        return default
    return default


def record_priority(rec: Any, default: int = PRIORITY_NORMAL) -> int:
    return headers_priority(getattr(rec, "headers", None), default)


class OverloadShed(RuntimeError):
    """Work refused or dropped by the overload plane. Carries the
    retry-after hint the REST fronts surface on a 429."""

    def __init__(self, msg: str, retry_after_s: float = 0.1):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class AdaptiveInflightBudget(InflightBudget):
    """AIMD concurrency limiter with the InflightBudget surface.

    The static cap asked the operator to guess a constant that is really a
    function of the stage's current latency; this derives it: the limit
    additively grows while observed latency sits inside the stage budget
    (``target_s``) and multiplicatively collapses when it doesn't — the
    TCP-congestion shape, which converges to the largest concurrency the
    stage sustains AT its latency budget and backs off within one window
    when the stage slows (InferLine's SLO-driven admission substrate).

    Sharing semantics are inherited: hand ONE instance to every
    ParallelRouter worker and the adaptive bound stays global across the
    pool, exactly like the static budget it replaces.
    """

    __slots__ = ("min_limit", "max_limit", "target_s", "beta", "step",
                 "good_window", "_good", "_cooldown_until", "_inc_next",
                 "increase_interval_s", "decrease_cooldown_s", "_clock")

    def __init__(
        self,
        limit: int,
        min_limit: int | None = None,
        max_limit: int | None = None,
        target_s: float = 0.05,
        beta: float = 0.7,
        step: int | None = None,
        good_window: int = 8,
        decrease_cooldown_s: float | None = None,
        increase_interval_s: float = 0.0,
        registry=None,
        stage: str = "router",
        clock: Callable[[], float] = time.monotonic,
    ):
        super().__init__(limit, registry=registry, stage=stage)
        self.min_limit = int(min_limit if min_limit is not None
                             else max(1, limit // 8))
        self.max_limit = int(max_limit if max_limit is not None
                             else 4 * limit)
        self.target_s = float(target_s)
        self.beta = float(beta)
        self.step = int(step if step is not None else max(1, limit // 16))
        self.good_window = int(good_window)
        self.increase_interval_s = float(increase_interval_s)
        # one decrease per ~stage round trip: a single slow burst's many
        # observations must cost ONE multiplicative cut, not limit→min
        self.decrease_cooldown_s = float(
            decrease_cooldown_s if decrease_cooldown_s is not None
            else max(2.0 * self.target_s, 0.1)
        )
        self._clock = clock
        self._good = 0
        self._cooldown_until = 0.0
        self._inc_next = 0.0

    def rescale_ceiling(self, max_limit: int,
                        min_limit: int | None = None) -> None:
        """Re-bound the AIMD range live — the fleet plane's per-host
        admission actuator (ISSUE 16): each member runs its own AIMD
        budget under ``global_ceiling / live_members``, so a membership
        change rescales every survivor's ceiling instead of letting N-1
        hosts keep admitting as if the dead host still shared the load.
        The current limit clamps into the new range; AIMD keeps moving it
        from there (a sick host still sheds locally below its share)."""
        with self._mu:
            self.max_limit = max(1, int(max_limit))
            if min_limit is not None:
                self.min_limit = max(1, int(min_limit))
            self.min_limit = min(self.min_limit, self.max_limit)
            self.limit = max(self.min_limit, min(self.limit, self.max_limit))
            self._set_gauges_locked()

    def observe(self, latency_s: float) -> None:
        """Feed one stage-latency sample; adjusts the limit AIMD-style."""
        now = self._clock()
        with self._mu:
            if latency_s > self.target_s:
                self._good = 0
                if now >= self._cooldown_until:
                    self.limit = max(self.min_limit,
                                     int(self.limit * self.beta))
                    self._cooldown_until = now + self.decrease_cooldown_s
                    self._set_gauges_locked()
                return
            self._good += 1
            if self._good >= self.good_window and now >= self._inc_next:
                self._good = 0
                self._inc_next = now + self.increase_interval_s
                if self.limit < self.max_limit:
                    self.limit = min(self.max_limit, self.limit + self.step)
                    self._set_gauges_locked()


class DeadlinePolicy:
    """CoDel-style deadline-aware queue policy: drop-from-front when
    sojourn time exceeds the target, scaled per priority class.

    The classic failure this kills: a standing queue forms, every entry
    waits out the full backlog, and the pipeline serves exclusively stale
    work at 100% utilization (bufferbloat). Dropping the FRONT — the
    oldest, already-blown entries — keeps the work that can still meet
    its deadline flowing. Per-class target multipliers (bulk 1×, normal
    2×, critical 4×) make a growing backlog shed bulk first and critical
    last without a separate priority queue.
    """

    __slots__ = ("target_s", "scale")

    def __init__(self, target_s: float,
                 scale: tuple[float, float, float] = (1.0, 2.0, 4.0)):
        self.target_s = float(target_s)
        self.scale = scale

    def cutoff_s(self, priority: int) -> float:
        return self.target_s * self.scale[
            min(len(self.scale) - 1, max(0, priority))]

    def should_drop(self, sojourn_s: float, priority: int) -> bool:
        return sojourn_s > self.cutoff_s(priority)


def _shed_counter(registry):
    return registry.counter(
        "ccfd_shed_total",
        "rows shed by the overload plane, by priority class and stage "
        "(deadline = CoDel sojourn expiry — the row went stale waiting, "
        "a fate not an admission choice; budget = in-flight bound "
        "victim selection; batcher = serving queue policy; rest = REST "
        "admission 429s)",
    )


def _admission_counter(registry):
    return registry.counter(
        "ccfd_admission_total",
        "admission decisions in rows by stage, priority and decision",
    )


def _bulk_ceiling_gauge(registry):
    return registry.gauge(
        "ccfd_bulk_ceiling",
        "operator-settable bulk admission ceiling by stage: the fraction "
        "of the stage's adaptive budget that bulk-class work (replay "
        "re-drives, backtests) may occupy — the replay plane's pacing "
        "actuator; 1.0 means bulk is bounded only by priority shedding",
    )


class OverloadControl:
    """Router/bus-side overload plane; ONE instance per router pool.

    Owns the shared adaptive budget, the bus deadline policy, the
    priority-shedding victim selection and the dispatch watchdog, plus
    the ``ccfd_*`` overload metrics. ParallelRouter hands the same
    instance to every worker, so — like PR 3's budget/breaker — the
    admission bound and the AIMD evidence stay global.
    """

    def __init__(
        self,
        registry,
        budget: AdaptiveInflightBudget,
        codel: DeadlinePolicy | None = None,
        dispatch_deadline_ms: float = 0.0,
        dispatch_threads: int = 4,
        clock: Callable[[], float] = time.time,
    ):
        self.registry = registry
        self.budget = budget
        self.codel = codel
        self.dispatch_deadline_s = max(0.0, float(dispatch_deadline_ms)) / 1e3
        # sacrificial-thread pool for the watchdog: sized to the worker
        # count (from_config) — the dispatcher's deadline covers queue
        # wait, so a pool smaller than the concurrently-dispatching
        # workers would turn healthy busy-queueing into spurious
        # ScorerTimeout kills that trip the breaker
        self.dispatch_threads = max(1, int(dispatch_threads))
        self._clock = clock  # wall clock: record timestamps are time.time()
        self._c_shed = _shed_counter(registry)
        self._c_admit = _admission_counter(registry)
        self._c_inversions = registry.counter(
            "ccfd_priority_inversions_total",
            "batches where a higher-priority row was shed while a "
            "lower-priority one was admitted — must stay 0; a nonzero "
            "value means the victim selection is broken",
        )
        self._c_dispatch_timeout = registry.counter(
            "ccfd_dispatch_timeout_total",
            "router scorer dispatches killed by the watchdog deadline "
            "(each trips the scorer-edge breaker instead of stalling a "
            "worker)",
        )
        self._dispatcher = None
        self._mu = threading.Lock()
        # bulk ceiling (replay pacing hook): the fraction of the adaptive
        # budget limit that bulk rows may occupy within one poll's
        # admission — live (normal/critical) traffic keeps the rest of
        # the stage no matter how hard a replay saturates the bus
        self._bulk_ceiling = 1.0
        self._g_bulk_ceiling = _bulk_ceiling_gauge(registry)
        self._g_bulk_ceiling.set(1.0, labels={"stage": "bus"})
        # incident flight recorder (observability/incident.py): when wired
        # by the operator, every watchdog kill snapshots the system state
        # into the recorder's ring — post-mortem evidence for hung-
        # dispatch kills, not only SLO breaches
        self.recorder = None

    @staticmethod
    def from_config(cfg, registry, max_batch: int = 4096,
                    workers: int = 1) -> "OverloadControl | None":
        """The operator/CLI construction path. None when overload control
        is disabled (CCFD_OVERLOAD=0 / CR ``overload.enabled: false``) —
        callers then keep the static-budget semantics."""
        if not getattr(cfg, "overload_enabled", True):
            return None
        workers = max(1, int(workers))
        # initial limit == the static default the adaptive budget replaces
        # (2×max_batch per worker: one batch in flight + one fresh poll)
        initial = 2 * max_batch * workers
        min_l = cfg.overload_min_inflight or max_batch
        max_l = cfg.overload_max_inflight or 4 * initial
        budget = AdaptiveInflightBudget(
            initial, min_limit=min_l, max_limit=max_l,
            target_s=cfg.overload_target_ms / 1e3,
            registry=registry, stage="router",
        )
        codel = (DeadlinePolicy(cfg.overload_codel_target_ms / 1e3)
                 if cfg.overload_codel_target_ms > 0 else None)
        dd = cfg.overload_dispatch_deadline_ms
        if dd < 0:  # auto: track the server-side SELDON_TIMEOUT resolution
            dd = cfg.scorer_dispatch_deadline_ms() or 0.0
        return OverloadControl(registry, budget, codel=codel,
                               dispatch_deadline_ms=dd,
                               dispatch_threads=max(4, workers))

    # -- bus-record admission ---------------------------------------------
    def admit(self, records: list,
              prepaid: bool = False) -> tuple[list, int]:
        """One poll's records -> (admitted survivors in arrival order,
        rows shed). On return the shared budget holds a reservation for
        exactly the survivors; the caller releases len(survivors) once
        they are fully routed.

        ``prepaid=True`` is the router's poll path: the loop reserved the
        budget BEFORE consuming (so overload never forces shedding rows
        of every priority at once), and this call releases the shed
        rows' share. ``prepaid=False`` reserves here, and when the limit
        can't cover the batch picks victims lowest-priority-first,
        oldest-first within a class (the PR-1 stalest-first rule, applied
        class by class).

        Shedding order: (1) deadline/CoDel — records whose bus sojourn
        exceeds their class cutoff (bulk 1x, normal 2x, critical 4x the
        target) drop from the front; (2) budget. By construction no
        admitted row has lower priority than any budget-shed row in the
        same batch; the inversion counter is the tripwire proving it
        stayed that way.
        """
        n = len(records)
        if n == 0:
            return records, 0
        pris = [record_priority(r) for r in records]
        shed_by: dict[tuple[int, str], int] = {}
        keep_idx = range(n)
        shed_rows = 0

        codel = self.codel
        if codel is not None:
            now = self._clock()
            # cheap pre-check on the OLDEST record: a multi-partition poll
            # concatenates partitions in partition order, not timestamp
            # order, so the batch head can be fresh while a lagging hot
            # partition's stale records hide behind it — min() over the
            # timestamps is what proves the batch fresh, not records[0]
            if now - min(r.timestamp for r in records) > codel.target_s:
                kept: list[int] = []
                for i in keep_idx:
                    if codel.should_drop(now - records[i].timestamp,
                                         pris[i]):
                        key = (pris[i], "deadline")
                        shed_by[key] = shed_by.get(key, 0) + 1
                        shed_rows += 1
                    else:
                        kept.append(i)
                keep_idx = kept

        keep_idx = list(keep_idx)
        frac = self._bulk_ceiling
        if frac < 1.0 and keep_idx:
            # cap bulk occupancy at frac x the CURRENT adaptive limit:
            # the ceiling tracks AIMD, so a stage that slows under live
            # load automatically tightens the replay share too
            cap = max(0, int(frac * self.budget.limit))
            kept: list[int] = []
            bulk_kept = 0
            for i in keep_idx:
                if pris[i] == PRIORITY_BULK:
                    if bulk_kept >= cap:
                        key = (pris[i], "bulk_ceiling")
                        shed_by[key] = shed_by.get(key, 0) + 1
                        shed_rows += 1
                        continue
                    bulk_kept += 1
                kept.append(i)
            keep_idx = kept
        if prepaid:
            # every consumed row was reserved at poll time; hand the shed
            # rows' reservation back
            if shed_rows:
                self.budget.release(shed_rows)
        else:
            granted = self.budget.reserve(len(keep_idx))
            if granted < len(keep_idx):
                excess = len(keep_idx) - granted
                # victims: lowest class first; within a class the OLDEST
                # first (stable index order == arrival order)
                order = sorted(keep_idx, key=lambda i: (pris[i], i))
                victims = set(order[:excess])
                max_shed_p = max(pris[i] for i in victims)
                survivors = [i for i in keep_idx if i not in victims]
                if survivors and min(
                        pris[i] for i in survivors) < max_shed_p:
                    self._c_inversions.inc()
                for i in victims:
                    key = (pris[i], "budget")
                    shed_by[key] = shed_by.get(key, 0) + 1
                shed_rows += excess
                keep_idx = survivors

        for (p, stage), count in shed_by.items():
            self._c_shed.inc(count, labels={
                "priority": PRIORITY_NAMES[p], "stage": stage})
            self._c_admit.inc(count, labels={
                "stage": "bus", "priority": PRIORITY_NAMES[p],
                "decision": "shed"})
        if keep_idx:
            admit_by: dict[int, int] = {}
            for i in keep_idx:
                admit_by[pris[i]] = admit_by.get(pris[i], 0) + 1
            for p, count in admit_by.items():
                self._c_admit.inc(count, labels={
                    "stage": "bus", "priority": PRIORITY_NAMES[p],
                    "decision": "admit"})
        if len(keep_idx) == n:
            return records, 0
        return [records[i] for i in keep_idx], shed_rows

    # -- bulk ceiling (the replay plane's pacing actuator) -----------------
    def set_bulk_ceiling(self, frac: float) -> None:
        """Clamp bulk-class bus admission to ``frac`` of the adaptive
        budget limit (0..1). 1.0 restores shed-order-only semantics."""
        frac = min(1.0, max(0.0, float(frac)))
        self._bulk_ceiling = frac
        self._g_bulk_ceiling.set(frac, labels={"stage": "bus"})

    @property
    def bulk_ceiling(self) -> float:
        return self._bulk_ceiling

    # -- stage feedback ----------------------------------------------------
    def observe_stage(self, latency_s: float) -> None:
        """Feed a scorer-stage latency sample into the AIMD budget."""
        self.budget.observe(latency_s)

    # -- dispatch watchdog -------------------------------------------------
    def bounded_dispatch(self, fn: Callable[[], Any],
                         deadline_s: float | None = None) -> Any:
        """Run a device dispatch under the watchdog deadline. On expiry the
        call raises (the router's ladder records a scorer-edge failure, so
        a hung dispatch trips the existing breaker instead of stalling the
        worker forever), the timeout is counted, and the deadline itself is
        fed to AIMD as the worst-possible latency sample.

        ``deadline_s`` overrides the plane's standing deadline for ONE
        call — the heal supervisor's canary dispatch (runtime/heal.py)
        rides this watchdog with its own (tighter) budget, so canary
        kills share the timeout counter, the AIMD feedback and the
        flight-recorder snapshot hook with serving kills."""
        if deadline_s is None:
            deadline_s = self.dispatch_deadline_s
        if deadline_s <= 0:
            return fn()
        from ccfd_tpu.serving.dispatch import DeviceDispatcher, ScorerTimeout

        if self._dispatcher is None:
            with self._mu:
                if self._dispatcher is None:
                    self._dispatcher = DeviceDispatcher(
                        max_threads=self.dispatch_threads,
                        name="ccfd-router-dispatch")
        try:
            return self._dispatcher.call(fn, deadline_s)
        except ScorerTimeout:
            self._c_dispatch_timeout.inc()
            self.budget.observe(deadline_s + self.budget.target_s)
            if self.recorder is not None:
                try:
                    self.recorder.note_dispatch_timeout()
                except Exception:  # noqa: BLE001 - evidence capture must
                    pass           # never mask the timeout signal
            raise


class AdmissionGate:
    """Serving-side (REST) admission: request-atomic reserve against an
    adaptive serving budget with priority-tiered utilization ceilings.

    Bulk requests are refused once the stage is half full, normal at 90%,
    critical only at the full limit — under load the 429s land on the
    traffic that can retry cheapest. A lone oversize request always
    admits (``try_reserve``'s empty-pass rule), so the gate can never
    starve a request bigger than the adapted limit.
    """

    UTIL_CEILING = {PRIORITY_BULK: 0.5, PRIORITY_NORMAL: 0.9,
                    PRIORITY_CRITICAL: 1.0}

    def __init__(self, budget: AdaptiveInflightBudget, registry,
                 stage: str = "rest", retry_after_s: float = 0.25):
        self.budget = budget
        self.stage = stage
        self.retry_after_s = float(retry_after_s)
        self._c_admit = _admission_counter(registry)
        self._c_shed = _shed_counter(registry)
        # per-instance ceilings so the replay plane can tighten/relax the
        # bulk share live without touching the class default
        self._ceilings = dict(self.UTIL_CEILING)
        self._g_bulk_ceiling = _bulk_ceiling_gauge(registry)
        self._g_bulk_ceiling.set(self._ceilings[PRIORITY_BULK],
                                 labels={"stage": self.stage})

    @staticmethod
    def from_config(cfg, registry, max_rows: int) -> "AdmissionGate | None":
        if not getattr(cfg, "overload_enabled", True):
            return None
        budget = AdaptiveInflightBudget(
            4 * max_rows, min_limit=max_rows, max_limit=16 * max_rows,
            target_s=cfg.overload_serve_target_ms / 1e3,
            registry=registry, stage="serving",
        )
        return AdmissionGate(budget, registry)

    def set_bulk_ceiling(self, frac: float) -> None:
        """Move the bulk utilization ceiling live (0..1) — the serving-
        side half of the replay pacing knob."""
        frac = min(1.0, max(0.0, float(frac)))
        self._ceilings[PRIORITY_BULK] = frac
        self._g_bulk_ceiling.set(frac, labels={"stage": self.stage})

    @property
    def bulk_ceiling(self) -> float:
        return self._ceilings[PRIORITY_BULK]

    def try_admit(self, rows: int, priority: int = PRIORITY_NORMAL) -> bool:
        ceiling = self._ceilings.get(priority, 0.9)
        ok = self.budget.try_reserve(rows, ceiling=ceiling)
        name = PRIORITY_NAMES.get(priority, "normal")
        self._c_admit.inc(rows, labels={
            "stage": self.stage, "priority": name,
            "decision": "admit" if ok else "reject"})
        if not ok:
            self._c_shed.inc(rows, labels={
                "priority": name, "stage": self.stage})
        return ok

    def release(self, rows: int) -> None:
        self.budget.release(rows)

    def observe(self, latency_s: float) -> None:
        self.budget.observe(latency_s)

    def refusal(self) -> OverloadShed:
        return OverloadShed("serving stage overloaded",
                            retry_after_s=self.retry_after_s)
