"""Durable-state integrity plane: checksummed artifacts, quarantine,
last-good recovery.

PR 1 made the network edges fallible and PR 11 made the device fallible,
but every durable artifact the platform trusts at restart — champion
checkpoints, the ``versions.json`` lineage, recovery cuts, engine
snapshots, the usertask/drift npz files — was read back with zero
integrity verification: a bit-flipped ``params.npz`` or a torn lineage
file either crashed bring-up or silently served garbage params. The bus
log already shows the house style (CRC-framed records, torn tails
truncated to the valid prefix on reopen, ``bus/log.py``); this module
extends that guarantee to everything else on disk and is the ONE seam
every persistent writer/reader goes through.

Three layers:

- :func:`atomic_write_bytes` — the atomic-write idiom the codebase had
  hand-rolled in eight places, centralized and hardened: unique tmp +
  write + **fsync** + rename (the hand-rolled copies skipped the fsync,
  so a power loss could survive the rename but not the data — exactly
  the torn file the read side then has to catch). Every storage fault in
  the taxonomy (``runtime/faults.py`` storage class: ``torn_write``,
  ``rename_lost``, ``bitrot``, ``enospc``, ``fsync_fail``,
  ``slow_disk``) injects HERE, so the whole failure surface is drillable
  on CPU CI.
- :func:`write_artifact` / :func:`read_artifact` — the payload is framed
  under a one-line sha256 header (``CCFDSUM1 <hex> <len>\\n``), and the
  read side VERIFIES it: a corrupt file is **quarantined** (renamed to
  ``*.corrupt``, counted in ``ccfd_storage_corrupt_total{artifact}``,
  reported to the FlightRecorder) and the read **falls back to the
  last-good retained generation** instead of crashing bring-up or
  serving the corruption. A file without the frame reads as a legacy
  artifact (accepted, counted unverified) so pre-existing state keeps
  loading.
- generation retention — every :func:`write_artifact` also lands a copy
  at ``<path>.g<seq>`` and prunes past ``retain`` (default 3), the way
  ``CheckpointManager.keep`` already retains step dirs, so single-file
  artifacts (lineage, recovery cuts, engine snapshots) always have a
  last-good to fall back to.

Writes are **best-effort by default**: the in-memory state every caller
here holds is authoritative, and a full disk (or an injected
``enospc``) must degrade durability — counted in
``ccfd_storage_write_errors_total{artifact}`` — not crash the serving
plane. Interchange documents read by humans/Grafana (incident bundles,
profile artifacts) keep their plain-JSON bodies and get a ``.sha256``
sidecar instead of a frame (:func:`write_json_interchange`).

Metrics ride a process-wide tally (this module is called from
constructors that hold no registry); the operator binds the scraped
registry via :func:`bind_registry`, which replays the counts collected
before binding. :func:`sweep_tmp` removes the orphan ``*.tmp`` debris a
crash mid-write leaves behind (``ccfd_storage_tmp_swept_total``) and is
called from the stateful components' constructors at bring-up.

When NOTHING verifies — every generation of the champion checkpoint is
corrupt — serving unverified params is not an option for a fraud
system: :class:`StoragePinGate` pins the router's degradation ladder to
the rules tier through the PR 11 heal-gate seam (``device_allowed`` +
the new ``host_allowed``) until a verified tree is published again.
"""

from __future__ import annotations

import errno
import hashlib
import itertools
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Mapping

log = logging.getLogger(__name__)

MAGIC = b"CCFDSUM1 "

# artifact-labelled metric short names; _PLAIN have no labels
_ARTIFACT_METRICS = ("corrupt", "fallback", "write_errors", "verified",
                     "unverified")
_PLAIN_METRICS = ("tmp_swept", "log_truncated_records")


class CorruptArtifactError(Exception):
    """No verifiable copy of a durable artifact exists (the main file and
    every retained generation failed verification)."""


_mu = threading.RLock()
_counts: dict[tuple[str, str], int] = {}  # (metric, artifact|"") -> n
_registry = None
_prom: dict[str, Any] = {}
_recorder: Callable[[Mapping[str, Any]], Any] | None = None
_tmp_seq = itertools.count()
_defaults = {"retain": 3, "fsync": True, "sweep": True}


def configure(retain: int | None = None, fsync: bool | None = None,
              sweep: bool | None = None) -> None:
    """Set the module defaults (the operator feeds the CR ``durability:``
    block here). Per-call arguments still win."""
    if retain is not None:
        _defaults["retain"] = max(0, int(retain))
    if fsync is not None:
        _defaults["fsync"] = bool(fsync)
    if sweep is not None:
        _defaults["sweep"] = bool(sweep)


def default_retain() -> int:
    return int(_defaults["retain"])


def bind_registry(registry) -> None:
    """Attach a scraped registry: creates the ``ccfd_storage_*`` counters
    and replays any tallies collected before binding (constructors run
    before the operator can wire metrics).

    The tallies are PROCESS-lifetime by design — a re-bind (a second
    Platform brought up in the same process) replays the full history
    into the fresh registry, so absolute counter values span the
    process, like the fault plans' ``injected`` tallies. ``rate()``
    consumers are unaffected; in-process consumers wanting a window
    snapshot :func:`counts` and diff."""
    global _registry
    with _mu:
        _registry = registry
        _prom.clear()
        _prom["corrupt"] = registry.counter(
            "ccfd_storage_corrupt_total",
            "corrupt durable artifacts detected (and quarantined)")
        _prom["fallback"] = registry.counter(
            "ccfd_storage_fallback_total",
            "reads served from a last-good retained generation")
        _prom["write_errors"] = registry.counter(
            "ccfd_storage_write_errors_total",
            "durable writes that failed (artifact kept last-good)")
        _prom["verified"] = registry.counter(
            "ccfd_storage_verified_reads_total",
            "artifact reads with a matching sha256 frame")
        _prom["unverified"] = registry.counter(
            "ccfd_storage_unverified_reads_total",
            "legacy (unframed) artifact reads accepted unverified")
        _prom["tmp_swept"] = registry.counter(
            "ccfd_storage_tmp_swept_total",
            "orphaned *.tmp files removed by the startup sweep")
        _prom["log_truncated_records"] = registry.counter(
            "ccfd_storage_log_truncated_records_total",
            "valid bus-log records dropped past a mid-file corrupt frame")
        for (short, artifact), n in _counts.items():
            c = _prom.get(short)
            if c is None or n <= 0:
                continue
            if short in _ARTIFACT_METRICS:
                c.inc(n, labels={"artifact": artifact})
            else:
                c.inc(n)


def set_recorder(fn: Callable[[Mapping[str, Any]], Any] | None) -> None:
    """FlightRecorder hook: called with a trigger mapping (``type``,
    ``artifact``, ``path``) on every quarantine, so corruption lands a
    post-mortem bundle like any other incident."""
    global _recorder
    _recorder = fn


def note(metric: str, n: int = 1, artifact: str = "") -> None:
    """Count one integrity event (public: ``bus/log.py`` counts mid-file
    log corruption here)."""
    if n <= 0:
        return
    with _mu:
        _counts[(metric, artifact)] = _counts.get((metric, artifact), 0) + n
        c = _prom.get(metric)
        if c is not None:
            if metric in _ARTIFACT_METRICS:
                c.inc(n, labels={"artifact": artifact})
            else:
                c.inc(n)


def counts() -> dict[str, dict[str, int]]:
    """{metric: {artifact: n}} snapshot of every tally so far."""
    with _mu:
        out: dict[str, dict[str, int]] = {}
        for (metric, artifact), n in _counts.items():
            out.setdefault(metric, {})[artifact] = n
        return out


def _notify_quarantine(artifact: str, path: str, dest: str) -> None:
    rec = _recorder
    if rec is None:
        return
    try:
        rec({"type": "storage_corrupt", "artifact": artifact,
             "path": path, "quarantined_to": dest})
    except Exception:  # noqa: BLE001 - post-mortem plumbing must not
        log.exception("storage quarantine recorder hook failed")


# ---------------------------------------------------------------------------
# the atomic-write seam (all storage faults inject here)
# ---------------------------------------------------------------------------


def _storage_plan():
    from ccfd_tpu.runtime import faults

    return faults.storage_faults()


def _flip_byte(path: str) -> None:
    """In-place single-byte corruption of a landed file (the ``bitrot``
    injection; also the drill helper tools/tests corrupt artifacts with)."""
    try:
        size = os.path.getsize(path)
        if size == 0:
            return
        off = size // 2
        with open(path, "r+b") as f:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")
    except OSError:
        log.exception("bitrot injection failed for %s", path)


def flip_bytes(path: str) -> None:
    """Deliberately corrupt an on-disk artifact (drills/tests)."""
    _flip_byte(path)


def atomic_write_bytes(path: str, data: bytes, fsync: bool | None = None,
                       artifact: str = "artifact") -> None:
    """Unique tmp + write + fsync + rename. Raises OSError on failure
    (injected or real); a failed write never touches the previous
    artifact, though it may leave an orphan ``*.tmp`` for the startup
    sweep — exactly what a crash mid-write leaves."""
    fsync = _defaults["fsync"] if fsync is None else bool(fsync)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    plan = _storage_plan()

    def draw(kind: str):
        return plan.draw(kind) if plan is not None else None

    s = draw("slow_disk")
    if s is not None:
        time.sleep(s.ms / 1e3)
    if draw("enospc") is not None:
        raise OSError(errno.ENOSPC, "injected ENOSPC", path)
    tmp = f"{path}.{os.getpid()}.{next(_tmp_seq)}.tmp"
    torn = draw("torn_write")
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    try:
        if torn is not None:
            # the crash-mid-write case: a prefix lands, the process dies
            # before the rename — the artifact keeps its previous bytes
            # and the orphan tmp waits for the sweep
            os.write(fd, data[: max(0, int(len(data) * torn.frac))])
            raise OSError(errno.EIO, "injected torn write", tmp)
        os.write(fd, data)
        if fsync:
            if draw("fsync_fail") is not None:
                raise OSError(errno.EIO, "injected fsync failure", tmp)
            os.fsync(fd)
    finally:
        os.close(fd)
    if draw("rename_lost") is not None:
        # the metadata-lost case: data was written and synced but the
        # rename never lands (journal lost on power cut) — the caller
        # believes the write succeeded, the artifact keeps its previous
        # bytes, the tmp is crash debris for the sweep
        return
    os.replace(tmp, path)
    if fsync:
        # the rename itself must survive a host crash: sync the directory
        try:
            dfd = os.open(d, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:  # pragma: no cover - platform-dependent
            pass
    if draw("bitrot") is not None:
        # latent media corruption surfacing after a successful write —
        # the read side's quarantine + last-good fallback must catch it
        _flip_byte(path)


# ---------------------------------------------------------------------------
# framed artifacts + generation retention
# ---------------------------------------------------------------------------


def frame(payload: bytes) -> bytes:
    """``CCFDSUM1 <sha256hex> <len>\\n<payload>`` — self-verifying in one
    file, so there is no payload-vs-sidecar rename race to mis-read."""
    h = hashlib.sha256(payload).hexdigest()
    return MAGIC + h.encode() + (" %d\n" % len(payload)).encode() + payload


def parse_frame(data: bytes) -> tuple[bytes | None, bool]:
    """-> (payload, framed). ``(data, False)`` for a legacy (unframed)
    file; ``(None, True)`` for a framed file that fails verification
    (torn, truncated, bit-flipped)."""
    if not data.startswith(MAGIC):
        return data, False
    nl = data.find(b"\n", len(MAGIC))
    if nl < 0:
        return None, True
    try:
        hexdigest, length = data[len(MAGIC):nl].split()
        length = int(length)
    except ValueError:
        return None, True
    payload = data[nl + 1:]
    if (len(payload) != length
            or hashlib.sha256(payload).hexdigest() != hexdigest.decode(
                "ascii", "replace")):
        return None, True
    return payload, True


def scan_frames(data: bytes) -> tuple[list[tuple[int, bytes]], int, bool]:
    """Streaming scan of CONCATENATED :func:`frame` blocks (append-only
    logs, e.g. the decision-audit segments) -> ``([(start_offset,
    payload), ...], valid_prefix_bytes, torn)``. Verification stops at
    the first bad frame: in an append-only file everything after it
    postdates the corruption and is unreachable — the caller truncates
    to the valid prefix (the bus-log reopen contract). One scanner so
    the frame format has a single owner (:func:`parse_frame` handles
    the one-frame-per-file artifacts)."""
    frames: list[tuple[int, bytes]] = []
    pos = 0
    n = len(data)
    while pos < n:
        if not data.startswith(MAGIC, pos):
            return frames, pos, True
        nl = data.find(b"\n", pos + len(MAGIC))
        if nl < 0:
            return frames, pos, True
        try:
            hexdigest, length = data[pos + len(MAGIC):nl].split()
            length = int(length)
        except ValueError:
            return frames, pos, True
        end = nl + 1 + length
        if end > n:
            return frames, pos, True
        payload = data[nl + 1:end]
        if hashlib.sha256(payload).hexdigest() != hexdigest.decode(
                "ascii", "replace"):
            return frames, pos, True
        frames.append((pos, payload))
        pos = end
    return frames, pos, False


def _generations(path: str) -> list[tuple[int, str]]:
    """Retained generations of ``path``, ascending ``[(seq, path)]``."""
    d = os.path.dirname(os.path.abspath(path))
    base = os.path.basename(path) + ".g"
    out: list[tuple[int, str]] = []
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for name in names:
        if name.startswith(base):
            tail = name[len(base):]
            if tail.isdigit():
                out.append((int(tail), os.path.join(d, name)))
    return sorted(out)


def has_generations(path: str) -> bool:
    return bool(_generations(path))


def write_artifact(path: str, payload: bytes, artifact: str = "artifact",
                   retain: int | None = None, fsync: bool | None = None,
                   best_effort: bool = True) -> bool:
    """Framed, checksummed, atomic write + generation retention. Returns
    False (and counts ``write_errors``) when the write failed and
    ``best_effort`` — the previous artifact (or its generations) stays
    the last-good state a reader falls back to."""
    data = frame(payload)
    try:
        atomic_write_bytes(path, data, fsync=fsync, artifact=artifact)
    except OSError as e:
        note("write_errors", artifact=artifact)
        log.error("durable write of %s (%s) failed: %s — keeping last-good",
                  path, artifact, e)
        if not best_effort:
            raise
        return False
    r = _defaults["retain"] if retain is None else max(0, int(retain))
    if r > 0:
        try:
            # a full SECOND copy, deliberately not an os.link of the main
            # file: a hard link shares the inode, so later bitrot of the
            # shared extent would corrupt main AND its newest generation
            # together — the exact failure the generation exists to
            # survive. Artifacts at this seam are small, low-rate JSON/
            # npz; the doubled write is the price of a physically
            # independent last-good copy.
            gens = _generations(path)
            seq = (gens[-1][0] + 1) if gens else 1
            atomic_write_bytes(f"{path}.g{seq:08d}", data, fsync=fsync,
                               artifact=artifact)
            for _s, p in _generations(path)[:-r]:
                try:
                    os.unlink(p)
                except OSError:
                    pass
        except OSError as e:
            note("write_errors", artifact=artifact)
            log.warning("generation retention for %s failed: %s", path, e)
    return True


def _quarantine(path: str, artifact: str) -> None:
    dest = path + ".corrupt"
    try:
        os.replace(path, dest)
    except OSError:
        dest = "<unmovable>"
    note("corrupt", artifact=artifact)
    log.error("corrupt %s artifact %s quarantined to %s", artifact, path,
              dest)
    _notify_quarantine(artifact, path, dest)


def read_artifact(path: str, artifact: str = "artifact",
                  fallback: bool = True, quarantine: bool = True) -> bytes:
    """Verified read. A framed file that fails its sha256 is quarantined
    (``*.corrupt``) and the newest verifiable retained generation is
    served instead (``ccfd_storage_fallback_total``). Raises
    FileNotFoundError when nothing was ever written, and
    :class:`CorruptArtifactError` when data existed but no copy
    verifies. ``quarantine=False`` peeks without touching disk state
    (best-effort probes); ``fallback=False`` raises on the main file's
    verdict alone (artifacts with their own retention, e.g. checkpoint
    step dirs)."""
    data: bytes | None = None
    read_failed = False
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        pass
    except OSError as e:
        # an UNREADABLE main file (EIO from dying media, EACCES) is the
        # hardware-failure case this plane exists for: treat it exactly
        # like a failed checksum — count, quarantine best-effort, and
        # fall back to the retained generations instead of propagating
        read_failed = True
        log.error("%s artifact %s unreadable (%s)", artifact, path, e)
    if data is not None:
        payload, framed = parse_frame(data)
        if payload is not None:
            note("verified" if framed else "unverified", artifact=artifact)
            return payload
    if data is not None or read_failed:
        if quarantine:
            _quarantine(path, artifact)
        else:
            note("corrupt", artifact=artifact)
    if not fallback:
        if data is None and not read_failed:
            raise FileNotFoundError(path)
        raise CorruptArtifactError(
            f"{artifact} artifact {path} failed verification")
    gens = _generations(path)
    for seq, gp in reversed(gens):
        try:
            with open(gp, "rb") as f:
                gdata = f.read()
        except OSError:
            continue
        payload, framed = parse_frame(gdata)
        if payload is not None and framed:
            note("fallback", artifact=artifact)
            log.warning("%s artifact %s served from last-good generation "
                        "g%d", artifact, path, seq)
            return payload
        # a corrupt generation must not be re-tried on every read
        note("corrupt", artifact=artifact)
        if quarantine:
            try:
                os.replace(gp, gp + ".corrupt")
            except OSError:
                pass
    if data is None and not read_failed and not gens:
        raise FileNotFoundError(path)
    raise CorruptArtifactError(
        f"no verifiable copy of {artifact} artifact {path}")


def verify_file(path: str) -> bool | None:
    """Peek verification: None when missing, True for a verified frame OR
    a legacy unframed file (nothing to check against), False when a
    frame fails its checksum. Never mutates disk state."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return None
    except OSError:
        return False
    payload, _framed = parse_frame(data)
    return payload is not None


def write_json_artifact(path: str, doc: Any, artifact: str = "artifact",
                        retain: int | None = None, fsync: bool | None = None,
                        best_effort: bool = True, **dump_kw: Any) -> bool:
    return write_artifact(
        path, json.dumps(doc, **dump_kw).encode(), artifact=artifact,
        retain=retain, fsync=fsync, best_effort=best_effort)


def read_json_artifact(path: str, artifact: str = "artifact",
                       fallback: bool = True, quarantine: bool = True) -> Any:
    return json.loads(read_artifact(path, artifact=artifact,
                                    fallback=fallback,
                                    quarantine=quarantine))


# ---------------------------------------------------------------------------
# interchange documents (plain body + .sha256 sidecar)
# ---------------------------------------------------------------------------


def write_json_interchange(path: str, doc: Any, artifact: str = "interchange",
                           best_effort: bool = True, **dump_kw: Any) -> bool:
    """Crash-safe write for documents external readers ``json.load``
    directly (incident bundles, profile artifacts, bench JSON): the body
    stays plain JSON; integrity rides a ``<path>.sha256`` sidecar written
    AFTER the body, so every crash window leaves either the old pair or
    a new body whose missing/stale sidecar reads as unverified — never a
    false quarantine of good data."""
    dump_kw.setdefault("indent", 1)
    body = (json.dumps(doc, **dump_kw) + "\n").encode()
    try:
        # remove the stale sidecar first: a crash after the body rename
        # must not leave the OLD hash beside the NEW body
        try:
            os.unlink(path + ".sha256")
        except FileNotFoundError:
            pass
        atomic_write_bytes(path, body, artifact=artifact)
        atomic_write_bytes(path + ".sha256",
                           hashlib.sha256(body).hexdigest().encode() + b"\n",
                           artifact=artifact)
    except OSError as e:
        note("write_errors", artifact=artifact)
        log.error("interchange write of %s failed: %s", path, e)
        if not best_effort:
            raise
        return False
    return True


def verify_interchange(path: str) -> bool | None:
    """True/False per the sidecar; None when the file or its sidecar is
    missing (legacy / mid-crash window: accept unverified)."""
    try:
        with open(path, "rb") as f:
            body = f.read()
        with open(path + ".sha256", "rb") as f:
            want = f.read().strip().decode("ascii", "replace")
    except FileNotFoundError:
        return None
    except OSError:
        return False
    return hashlib.sha256(body).hexdigest() == want


# ---------------------------------------------------------------------------
# directory manifests (orbax checkpoint dirs: many files, none ours to frame)
# ---------------------------------------------------------------------------

MANIFEST_NAME = "ccfd_manifest.json"


def _dir_files(dirpath: str) -> list[str]:
    out = []
    for root, _dirs, files in os.walk(dirpath):
        for name in files:
            p = os.path.join(root, name)
            rel = os.path.relpath(p, dirpath)
            if rel == MANIFEST_NAME or rel.endswith(".tmp"):
                continue
            out.append(rel)
    return sorted(out)


def write_dir_manifest(dirpath: str, artifact: str = "checkpoint") -> bool:
    """Checksum manifest over every file in a directory artifact (the
    orbax checkpoint path — its internal files are not ours to frame)."""
    manifest: dict[str, Any] = {}
    try:
        for rel in _dir_files(dirpath):
            with open(os.path.join(dirpath, rel), "rb") as f:
                manifest[rel] = hashlib.sha256(f.read()).hexdigest()
    except OSError as e:
        note("write_errors", artifact=artifact)
        log.error("manifest build for %s failed: %s", dirpath, e)
        return False
    return write_json_artifact(os.path.join(dirpath, MANIFEST_NAME),
                               manifest, artifact=artifact, retain=0)


def verify_dir_manifest(dirpath: str, artifact: str = "checkpoint"
                        ) -> bool | None:
    """True/False per the manifest; None when no manifest exists (a
    legacy checkpoint dir: accepted unverified)."""
    mpath = os.path.join(dirpath, MANIFEST_NAME)
    try:
        manifest = read_json_artifact(mpath, artifact=artifact,
                                      fallback=False, quarantine=False)
    except FileNotFoundError:
        return None
    except (CorruptArtifactError, ValueError):
        return False
    try:
        for rel, want in manifest.items():
            with open(os.path.join(dirpath, rel), "rb") as f:
                if hashlib.sha256(f.read()).hexdigest() != want:
                    return False
    except OSError:
        return False
    return True


# ---------------------------------------------------------------------------
# orphan-tmp sweep
# ---------------------------------------------------------------------------


def sweep_tmp(*dirs: str, enabled: bool | None = None) -> int:
    """Remove orphaned ``*.tmp`` files a crash mid-write left behind
    (e.g. the offsets.log compaction tmp in bus/log.py). Startup-only by
    contract: live writers use unique tmp names and rename within the
    same call, so any ``*.tmp`` present when a component CONSTRUCTS is
    debris. Counted in ``ccfd_storage_tmp_swept_total``."""
    if not (_defaults["sweep"] if enabled is None else enabled):
        return 0
    n = 0
    for d in dirs:
        if not d:
            continue
        try:
            names = os.listdir(d)
        except OSError:
            continue
        for name in names:
            if not name.endswith(".tmp"):
                continue
            try:
                os.unlink(os.path.join(d, name))
                n += 1
            except OSError:
                pass
    if n:
        note("tmp_swept", n)
        log.warning("startup sweep removed %d orphaned tmp file(s) from %s",
                    n, ", ".join(d for d in dirs if d))
    return n


# ---------------------------------------------------------------------------
# the rules-tier pin for unverifiable serving state
# ---------------------------------------------------------------------------


class StoragePinGate:
    """Heal-gate-shaped pin (``device_allowed`` + ``host_allowed``): when
    NO champion checkpoint generation verifies, the router must pin to
    the rules tier — the host tier would forward the very same
    unverified tree. Armed by the lifecycle controller's restore path,
    cleared when a verified tree is published again."""

    def __init__(self, registry=None):
        self._mu = threading.Lock()
        self._pinned = False
        self.reason: str | None = None
        self.pins = 0
        self._g = None
        if registry is not None:
            self._g = registry.gauge(
                "ccfd_storage_pinned",
                "1 while serving is pinned to the rules tier because no "
                "durable params generation verifies",
            )
            self._g.set(0)

    @property
    def pinned(self) -> bool:
        with self._mu:
            return self._pinned

    def pin(self, reason: str) -> None:
        with self._mu:
            if not self._pinned:
                self.pins += 1
            self._pinned = True
            self.reason = reason
            if self._g is not None:
                self._g.set(1)
        log.error("storage pin: serving pinned to the rules tier (%s)",
                  reason)

    def unpin(self) -> None:
        with self._mu:
            was = self._pinned
            self._pinned = False
            self.reason = None
            if self._g is not None:
                self._g.set(0)
        if was:
            log.warning("storage pin cleared: verified params published")

    # the router's heal-gate surface
    def device_allowed(self) -> bool:
        return not self.pinned

    def host_allowed(self) -> bool:
        return not self.pinned


class ComposedHealGate:
    """AND-composition of heal-gate-shaped objects: the operator hands
    the router ONE gate built from the storage pin and (when the heal
    component is up) the DeviceSupervisor. ``host_allowed`` consults
    only gates that define it (the DeviceSupervisor pins the device but
    the host tier stays the heal ladder's fallback)."""

    def __init__(self, *gates: Any):
        self.gates = tuple(g for g in gates if g is not None)

    def device_allowed(self) -> bool:
        return all(g.device_allowed() for g in self.gates)

    def host_allowed(self) -> bool:
        return all(
            g.host_allowed() for g in self.gates
            if callable(getattr(g, "host_allowed", None))
        )
