"""Network-edge fault injection: degraded RPC hops, seeded and installable.

``runtime/chaos.py`` kills whole components; the far more common production
failure is a *sick edge* — a scorer endpoint that answers slowly, a
partitioned bus, a flaky engine hop. The reference has no story for either
(SURVEY.md §5: its resilience is k8s restartPolicy + Kafka redelivery).
This module makes degraded edges injectable on every client hop the
framework owns — router↔scorer (`serving/client.py` and the in-process
score_fn), router↔engine (`process/client.py` and the in-process
``EngineClient``), services↔bus (`bus/client.py`), producer↔store
(`store/client.py`) — so the circuit breakers and the router's degradation
ladder (`runtime/breaker.py`, `router/router.py`) are *exercised* in CI and
soaks instead of trusted.

Model: a ``FaultPlan`` maps edge names to ``FaultSpec``s (latency + jitter,
error rate, blackhole/partition, corrupt-response, slow-drip) and is parsed
from ``CCFD_FAULTS``::

    CCFD_FAULTS="scorer:latency=50,jitter=20,error=0.05;engine:blackhole"

A ``FaultInjector`` binds one edge of the plan around a client (or a bare
callable) and perturbs every call while the plan is ACTIVE. Plans are
seeded — victim timing and error draws are replayable — and activation is
a thread-safe toggle so the ChaosMonkey can drive fault *storms* (windows
of degradation) on a schedule, the edge-level analog of its kill schedule.

Injected failures raise :class:`InjectedFault` (a ``ConnectionError``), so
every client's existing transport-error handling — retries, breakers, the
router's tier ladder — engages exactly as it would for the real thing.
"""

from __future__ import annotations

import binascii
import random
import threading
import time
from typing import Any, Callable, Iterable, Mapping

import numpy as np


class InjectedFault(ConnectionError):
    """A fault-plan failure. Subclasses ConnectionError so client retry /
    breaker paths treat it exactly like a real transport error."""


# fault kinds a spec can carry; parse-time validation names them
_KINDS = ("latency", "jitter", "error", "blackhole", "corrupt", "drip",
          "stall")


class FaultSpec:
    """One edge's degradation profile. All times in milliseconds.

    - ``latency_ms`` fixed added delay per call
    - ``jitter_ms`` extra uniform delay in [0, jitter_ms)
    - ``error_rate`` probability a call raises :class:`InjectedFault`
    - ``blackhole`` the peer is partitioned: every call stalls ``stall_ms``
      (the SYN-timeout analog, bounded so tests stay fast) then raises
    - ``corrupt_rate`` probability a *response* comes back mangled (float
      arrays go NaN — silent corruption the validation layers must catch;
      anything else raises, the decode-error analog)
    - ``drip_ms`` slow drip: added delay GROWS by drip_ms per call while
      the plan is active (a degrading endpoint), capped at ``drip_cap_ms``
    """

    __slots__ = ("latency_ms", "jitter_ms", "error_rate", "blackhole",
                 "corrupt_rate", "drip_ms", "drip_cap_ms", "stall_ms")

    def __init__(
        self,
        latency_ms: float = 0.0,
        jitter_ms: float = 0.0,
        error_rate: float = 0.0,
        blackhole: bool = False,
        corrupt_rate: float = 0.0,
        drip_ms: float = 0.0,
        drip_cap_ms: float = 1000.0,
        stall_ms: float = 250.0,
    ):
        if not 0.0 <= error_rate <= 1.0:
            raise ValueError(f"error_rate {error_rate} outside [0, 1]")
        if not 0.0 <= corrupt_rate <= 1.0:
            raise ValueError(f"corrupt_rate {corrupt_rate} outside [0, 1]")
        for name, v in (("latency_ms", latency_ms), ("jitter_ms", jitter_ms),
                        ("drip_ms", drip_ms), ("drip_cap_ms", drip_cap_ms),
                        ("stall_ms", stall_ms)):
            if v < 0:
                raise ValueError(f"{name} must be >= 0, got {v}")
        self.latency_ms = float(latency_ms)
        self.jitter_ms = float(jitter_ms)
        self.error_rate = float(error_rate)
        self.blackhole = bool(blackhole)
        self.corrupt_rate = float(corrupt_rate)
        self.drip_ms = float(drip_ms)
        self.drip_cap_ms = float(drip_cap_ms)
        self.stall_ms = float(stall_ms)

    @staticmethod
    def parse(body: str) -> "FaultSpec":
        """``"latency=50,jitter=20,error=0.1,blackhole"`` -> FaultSpec.
        Bare ``blackhole``/``corrupt`` flags take their default strength."""
        kw: dict[str, Any] = {}
        for item in body.split(","):
            item = item.strip()
            if not item:
                continue
            key, sep, val = item.partition("=")
            key = key.strip()
            if key not in _KINDS:
                raise ValueError(
                    f"unknown fault kind {key!r}; known: {_KINDS}")
            if key == "blackhole":
                kw["blackhole"] = (val.strip().lower()
                                   not in ("0", "false", "no")
                                   if sep else True)
            elif key == "corrupt":
                kw["corrupt_rate"] = float(val) if sep else 1.0
            elif key == "error":
                kw["error_rate"] = float(val)
            elif key == "stall":
                kw["stall_ms"] = float(val)
            else:  # latency / jitter / drip
                kw[f"{key}_ms"] = float(val)
        return FaultSpec(**kw)

    def __repr__(self) -> str:  # debugging / soak reports
        parts = [f"{k}={getattr(self, k)}" for k in self.__slots__
                 if getattr(self, k)]
        return f"FaultSpec({', '.join(parts)})"


class FaultPlan:
    """Edge name -> FaultSpec, with a thread-safe activation toggle.

    ``"*"`` is the wildcard edge (applies to any edge without its own
    spec). A plan parsed from env starts ACTIVE (the operator asked for
    standing degradation); a plan handed to the ChaosMonkey for storm
    scheduling is usually built with ``active=False`` and toggled.
    """

    def __init__(self, specs: Mapping[str, FaultSpec] | None = None,
                 seed: int = 0, active: bool = True):
        self.specs = dict(specs or {})
        self.seed = int(seed)
        self._active = threading.Event()
        if active:
            self._active.set()
        self.activations = 0

    @staticmethod
    def from_string(text: str, seed: int = 0,
                    active: bool = True) -> "FaultPlan":
        """``"edge:kind=v,kind;edge2:kind"`` -> FaultPlan. Empty text means
        an empty (no-op) plan."""
        specs: dict[str, FaultSpec] = {}
        for part in text.split(";"):
            part = part.strip()
            if not part:
                continue
            edge, sep, body = part.partition(":")
            edge = edge.strip()
            if not edge or not sep:
                raise ValueError(
                    f"CCFD_FAULTS entry {part!r}: expected edge:spec")
            specs[edge] = FaultSpec.parse(body)
        return FaultPlan(specs, seed=seed, active=active)

    @staticmethod
    def from_env(env: Mapping[str, str] | None = None,
                 seed: int = 0) -> "FaultPlan":
        import os

        e = os.environ if env is None else env
        return FaultPlan.from_string(e.get("CCFD_FAULTS", ""), seed=seed)

    # -- activation (ChaosMonkey storm windows) ---------------------------
    @property
    def active(self) -> bool:
        return self._active.is_set()

    def activate(self) -> None:
        self.activations += 1
        self._active.set()

    def deactivate(self) -> None:
        self._active.clear()

    def spec_for(self, edge: str) -> FaultSpec | None:
        return self.specs.get(edge) or self.specs.get("*")

    def injector(self, edge: str, registry=None) -> "FaultInjector | None":
        """Injector bound to one edge, or None when the plan has nothing
        for it — callers then skip wrapping entirely (zero overhead)."""
        spec = self.spec_for(edge)
        if spec is None:
            return None
        return FaultInjector(self, edge, spec, registry=registry)


class FaultInjector:
    """Applies one edge's FaultSpec around calls.

    Deterministic per (plan seed, edge): the RNG seeds from
    ``seed ^ crc32(edge)`` so two runs with the same plan draw the same
    error sequence per edge regardless of edge iteration order.
    """

    def __init__(self, plan: FaultPlan, edge: str, spec: FaultSpec,
                 registry=None):
        self.plan = plan
        self.edge = edge
        self.spec = spec
        self._rng = random.Random(
            plan.seed ^ binascii.crc32(edge.encode()))
        self._mu = threading.Lock()
        self._calls_active = 0  # drip ramp position
        self.injected = 0       # lifetime count, any kind
        self._c_injected = None
        if registry is not None:
            self._c_injected = registry.counter(
                "faults_injected_total",
                "fault-plan perturbations by edge and kind",
            )

    def _count(self, kind: str) -> None:
        self.injected += 1
        if self._c_injected is not None:
            self._c_injected.inc(labels={"edge": self.edge, "kind": kind})

    def before(self) -> bool:
        """Pre-call perturbation: delay, blackhole, error draw. Returns
        whether the caller should corrupt the response (pass the flag to
        :meth:`after` — per-call state stays on the caller's stack so
        concurrent calls through one injector don't cross-attribute)."""
        if not self.plan.active:
            with self._mu:
                self._calls_active = 0  # drip ramp resets between storms
            return False
        s = self.spec
        with self._mu:
            n = self._calls_active
            self._calls_active = n + 1
            jitter = self._rng.random() * s.jitter_ms
            err_draw = self._rng.random()
            corrupt = self._rng.random() < s.corrupt_rate
        delay_ms = s.latency_ms + jitter + min(s.drip_ms * n, s.drip_cap_ms)
        if delay_ms > 0:
            self._count("latency")
            time.sleep(delay_ms / 1e3)
        if s.blackhole:
            self._count("blackhole")
            time.sleep(s.stall_ms / 1e3)
            raise InjectedFault(
                f"edge {self.edge!r} blackholed (injected partition)")
        if err_draw < s.error_rate:
            self._count("error")
            raise InjectedFault(f"edge {self.edge!r} injected error")
        return corrupt

    def after(self, result: Any, corrupt: bool) -> Any:
        """Post-call perturbation: corrupt the response in flight."""
        if not corrupt or not self.plan.active:
            return result
        self._count("corrupt")
        if isinstance(result, np.ndarray) and np.issubdtype(
                result.dtype, np.floating):
            # silent corruption: the payload decodes but the numbers are
            # garbage — exactly what response validation must catch
            return np.full_like(result, np.nan)
        raise InjectedFault(
            f"edge {self.edge!r} returned an undecodable response "
            "(injected corruption)")

    def run(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> Any:
        corrupt = self.before()
        return self.after(fn(*args, **kwargs), corrupt)

    def wrap_fn(self, fn: Callable[..., Any]) -> Callable[..., Any]:
        """Bare-callable edge (e.g. the router's in-process score_fn)."""
        def wrapped(*args: Any, **kwargs: Any) -> Any:
            return self.run(fn, *args, **kwargs)

        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapped

    def wrap(self, obj: Any, methods: Iterable[str] | None = None) -> Any:
        """Proxy an object, perturbing the named public methods (all public
        callables when ``methods`` is None). Everything else delegates, so
        the proxy keeps the wrapped client's full surface (e.g. the
        router's ``definitions`` probe on an engine)."""
        from ccfd_tpu.runtime.breaker import MethodProxy

        return MethodProxy(obj, self.run,
                           frozenset(methods) if methods else None)


# ---------------------------------------------------------------------------
# Device faults: the accelerator itself as a fallible component.
#
# The edge faults above perturb RPC hops; the failure taxonomy the heal
# ladder (runtime/heal.py) defends against lives BELOW every edge — the
# device wedges mid-dispatch, the allocator runs out of HBM, XLA re-traces
# in a storm, a host->device staging put fails. These inject at the three
# seams the serving stack owns (all drillable on CPU CI):
#
# - ``dispatch`` — the scorer's device-dispatch loop (Scorer.score_pipelined
#   / SeqScorer's chunk loop): ``device_hang`` stalls the dispatch past its
#   watchdog deadline; ``compile_stall`` stalls AND bills a synthetic
#   backend_compile event to the active compile_stage label, so the
#   compile-storm signal the DeviceSupervisor watches actually moves.
# - ``put`` — the staging seam (Scorer._put_batch / SeqScorer._put_hist):
#   ``put_fail`` raises, and the telemetry plane counts the failure
#   (ccfd_h2d_put_failures_total — the supervisor's put-failure signal).
# - telemetry — ``device_oom`` overlays allocator pressure onto
#   DeviceTelemetry.device_memory() (bytes_in_use ~= bytes_limit), the
#   OOM-pressure signal, since CPU backends report no allocator stats.
#
# A plan installs process-wide (install_device_faults) because the seams
# sit inside compiled-dispatch helpers no injector proxy can wrap; the
# activation toggle has the FaultPlan interface, so the ChaosMonkey (and
# tools/chaos_soak.py --device-faults) schedules device-fault storms with
# the same machinery that drives edge storms.
# ---------------------------------------------------------------------------

DEVICE_FAULT_KINDS = ("device_hang", "compile_stall", "device_oom",
                      "put_fail")


class DeviceFaultSpec:
    """Parameters for one device-fault kind. Times in milliseconds.

    - ``device_hang``: every dispatch stalls ``hang_ms`` (default 400 —
      comfortably past the CI-scale watchdog deadlines the drills use).
    - ``compile_stall``: every dispatch stalls ``stall_ms`` and records a
      synthetic backend_compile of that duration (a re-trace storm).
    - ``device_oom``: reported allocator pressure ``oom_ratio`` of
      bytes_limit (default 0.99 — past any sane quarantine threshold).
    - ``put_fail``: a staging put raises with probability ``rate``
      (default 1.0).
    """

    __slots__ = ("hang_ms", "stall_ms", "oom_ratio", "rate")

    def __init__(self, hang_ms: float = 400.0, stall_ms: float = 50.0,
                 oom_ratio: float = 0.99, rate: float = 1.0):
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"rate {rate} outside [0, 1]")
        if not 0.0 <= oom_ratio <= 1.0:
            raise ValueError(f"oom_ratio {oom_ratio} outside [0, 1]")
        for name, v in (("hang_ms", hang_ms), ("stall_ms", stall_ms)):
            if v < 0:
                raise ValueError(f"{name} must be >= 0, got {v}")
        self.hang_ms = float(hang_ms)
        self.stall_ms = float(stall_ms)
        self.oom_ratio = float(oom_ratio)
        self.rate = float(rate)

    @staticmethod
    def parse(body: str) -> "DeviceFaultSpec":
        """``"ms=400"`` / ``"ratio=0.95,rate=0.5"`` -> DeviceFaultSpec.
        ``ms`` sets both hang and stall times (one knob per kind in
        practice); empty body takes every default."""
        kw: dict[str, float] = {}
        for item in body.split(","):
            item = item.strip()
            if not item:
                continue
            key, sep, val = item.partition("=")
            key = key.strip()
            if not sep:
                raise ValueError(
                    f"device-fault option {item!r}: expected key=value")
            if key == "ms":
                kw["hang_ms"] = kw["stall_ms"] = float(val)
            elif key == "ratio":
                kw["oom_ratio"] = float(val)
            elif key == "rate":
                kw["rate"] = float(val)
            else:
                raise ValueError(
                    f"unknown device-fault option {key!r}; "
                    f"known: ms, ratio, rate")
        return DeviceFaultSpec(**kw)


class DeviceFaultPlan:
    """Active device-fault kinds + the FaultPlan activation interface
    (``activate``/``deactivate``/``active``/``activations``) so storm
    schedulers drive device faults exactly like edge faults."""

    def __init__(self, kinds: Mapping[str, DeviceFaultSpec] | None = None,
                 seed: int = 0, active: bool = True):
        for k in (kinds or {}):
            if k not in DEVICE_FAULT_KINDS:
                raise ValueError(
                    f"unknown device fault {k!r}; known: "
                    f"{DEVICE_FAULT_KINDS}")
        self.kinds = dict(kinds or {})
        self._rng = random.Random(seed)
        self._active = threading.Event()
        if active:
            self._active.set()
        self.activations = 0
        self.injected: dict[str, int] = {}
        self._oom_counted_epoch = -1  # activation epoch last counted

    @staticmethod
    def from_string(text: str, seed: int = 0,
                    active: bool = True) -> "DeviceFaultPlan":
        """``"device_hang:ms=400;put_fail"`` -> DeviceFaultPlan (the
        CCFD_DEVICE_FAULTS syntax). Empty text means an empty plan."""
        kinds: dict[str, DeviceFaultSpec] = {}
        for part in text.split(";"):
            part = part.strip()
            if not part:
                continue
            kind, _sep, body = part.partition(":")
            kinds[kind.strip()] = DeviceFaultSpec.parse(body)
        return DeviceFaultPlan(kinds, seed=seed, active=active)

    @property
    def active(self) -> bool:
        return self._active.is_set()

    def activate(self) -> None:
        self.activations += 1
        self._active.set()

    def deactivate(self) -> None:
        self._active.clear()

    def spec(self, kind: str) -> DeviceFaultSpec | None:
        """The kind's spec while the plan is ACTIVE, else None."""
        if not self._active.is_set():
            return None
        return self.kinds.get(kind)

    def _count(self, kind: str) -> None:
        self.injected[kind] = self.injected.get(kind, 0) + 1


_DEVICE_PLAN: DeviceFaultPlan | None = None


def install_device_faults(plan: DeviceFaultPlan | None) -> None:
    """Install (or, with None, clear) the process-wide device-fault plan
    the scorer seams consult. Process-wide because the seams live inside
    dispatch helpers built long before any injector could wrap them."""
    global _DEVICE_PLAN
    _DEVICE_PLAN = plan


def device_faults() -> DeviceFaultPlan | None:
    return _DEVICE_PLAN


def device_seam(seam: str) -> None:
    """Fault hook the scorer seams call: ``dispatch`` before each device
    dispatch, ``put`` before each staging put. No-op (one None check) with
    no active plan. ``put_fail`` raises :class:`InjectedFault` so the
    caller's transport-error handling (breaker, ladder, telemetry failure
    count) engages exactly as for a real staging failure."""
    plan = _DEVICE_PLAN
    if plan is None or not plan.active:
        return
    if seam == "dispatch":
        s = plan.spec("device_hang")
        if s is not None:
            plan._count("device_hang")
            time.sleep(s.hang_ms / 1e3)
        s = plan.spec("compile_stall")
        if s is not None:
            plan._count("compile_stall")
            # a re-trace storm: the dispatch pays a compile it shouldn't,
            # and the compile-attribution plane must SEE it (that rate is
            # the signal the DeviceSupervisor quarantines on)
            from ccfd_tpu.observability.profile import (
                record_synthetic_compile,
            )

            record_synthetic_compile(s.stall_ms / 1e3)
            time.sleep(s.stall_ms / 1e3)
    elif seam == "put":
        s = plan.spec("put_fail")
        if s is not None and plan._rng.random() < s.rate:
            plan._count("put_fail")
            raise InjectedFault("staging put failed (injected put_fail)")


# ---------------------------------------------------------------------------
# Storage faults: the DISK as a fallible component.
#
# The device class above injects at the scorer seams; the storage class
# injects at the durable-state seam every persistent writer/reader now
# shares (runtime/durability.py atomic_write_bytes). The taxonomy is the
# classic storage failure set, each drillable on CPU CI:
#
# - ``torn_write``  — the process dies mid-write: a prefix lands in the
#   tmp file, the rename never happens (orphan tmp for the startup
#   sweep; the artifact keeps its previous bytes).
# - ``rename_lost`` — data written and fsynced but the rename's metadata
#   never commits (power cut before the journal): the caller believes
#   the write succeeded, the artifact silently keeps its OLD contents.
# - ``bitrot``      — latent media corruption after a successful write:
#   the landed file gets a flipped byte, which the checksummed read side
#   must quarantine and recover from (last-good generation).
# - ``enospc``      — the volume is full: the write raises ENOSPC.
# - ``fsync_fail``  — the sync fails (dying disk, thin-provisioned
#   volume): the write raises EIO before the rename.
# - ``slow_disk``   — degraded I/O: every write stalls ``ms``.
#
# Same activation surface as the other plans, so the ChaosMonkey storm-
# schedules storage degradation windows with the machinery that already
# drives edge and device storms (CCFD_STORAGE_FAULTS env / CR
# ``chaos.storage_faults``; tools/chaos_soak.py --storage-faults).
# ---------------------------------------------------------------------------

STORAGE_FAULT_KINDS = ("torn_write", "rename_lost", "bitrot", "enospc",
                       "fsync_fail", "slow_disk")


class StorageFaultSpec:
    """Parameters for one storage-fault kind.

    - ``rate`` probability the fault fires per write (default 1.0)
    - ``ms``   added latency for ``slow_disk`` (default 25)
    - ``frac`` fraction of the payload a ``torn_write`` lands (default
      0.5 — enough bytes that a frame header parses but the checksum
      cannot)
    """

    __slots__ = ("rate", "ms", "frac")

    def __init__(self, rate: float = 1.0, ms: float = 25.0,
                 frac: float = 0.5):
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"rate {rate} outside [0, 1]")
        if not 0.0 <= frac <= 1.0:
            raise ValueError(f"frac {frac} outside [0, 1]")
        if ms < 0:
            raise ValueError(f"ms must be >= 0, got {ms}")
        self.rate = float(rate)
        self.ms = float(ms)
        self.frac = float(frac)

    @staticmethod
    def parse(body: str) -> "StorageFaultSpec":
        """``"rate=0.5,ms=10,frac=0.3"`` -> StorageFaultSpec; empty body
        takes every default."""
        kw: dict[str, float] = {}
        for item in body.split(","):
            item = item.strip()
            if not item:
                continue
            key, sep, val = item.partition("=")
            key = key.strip()
            if not sep:
                raise ValueError(
                    f"storage-fault option {item!r}: expected key=value")
            if key not in ("rate", "ms", "frac"):
                raise ValueError(
                    f"unknown storage-fault option {key!r}; "
                    f"known: rate, ms, frac")
            kw[key] = float(val)
        return StorageFaultSpec(**kw)


class StorageFaultPlan:
    """Active storage-fault kinds + the FaultPlan activation interface,
    so storm schedulers drive disk degradation exactly like edge and
    device faults."""

    def __init__(self, kinds: Mapping[str, StorageFaultSpec] | None = None,
                 seed: int = 0, active: bool = True):
        for k in (kinds or {}):
            if k not in STORAGE_FAULT_KINDS:
                raise ValueError(
                    f"unknown storage fault {k!r}; known: "
                    f"{STORAGE_FAULT_KINDS}")
        self.kinds = dict(kinds or {})
        self._rng = random.Random(seed)
        self._mu = threading.Lock()
        self._active = threading.Event()
        if active:
            self._active.set()
        self.activations = 0
        self.injected: dict[str, int] = {}

    @staticmethod
    def from_string(text: str, seed: int = 0,
                    active: bool = True) -> "StorageFaultPlan":
        """``"bitrot;torn_write:rate=0.5"`` -> StorageFaultPlan (the
        CCFD_STORAGE_FAULTS syntax). Empty text means an empty plan."""
        kinds: dict[str, StorageFaultSpec] = {}
        for part in text.split(";"):
            part = part.strip()
            if not part:
                continue
            kind, _sep, body = part.partition(":")
            kinds[kind.strip()] = StorageFaultSpec.parse(body)
        return StorageFaultPlan(kinds, seed=seed, active=active)

    @property
    def active(self) -> bool:
        return self._active.is_set()

    def activate(self) -> None:
        self.activations += 1
        self._active.set()

    def deactivate(self) -> None:
        self._active.clear()

    def draw(self, kind: str) -> StorageFaultSpec | None:
        """The kind's spec when the plan is active AND its rate draw
        fires — one call per write per kind (runtime/durability.py)."""
        if not self._active.is_set():
            return None
        s = self.kinds.get(kind)
        if s is None:
            return None
        with self._mu:
            if self._rng.random() >= s.rate:
                return None
            self.injected[kind] = self.injected.get(kind, 0) + 1
        return s


_STORAGE_PLAN: StorageFaultPlan | None = None


def install_storage_faults(plan: StorageFaultPlan | None) -> None:
    """Install (or, with None, clear) the process-wide storage-fault plan
    the durability seam consults. Process-wide for the same reason the
    device plan is: the seam sits inside constructors and module-level
    helpers no injector proxy could wrap."""
    global _STORAGE_PLAN
    _STORAGE_PLAN = plan


def storage_faults() -> StorageFaultPlan | None:
    return _STORAGE_PLAN


def device_oom_overlay() -> float | None:
    """The injected allocator-pressure ratio, or None. Consulted by
    DeviceTelemetry.device_memory() so the OOM signal is drillable on
    backends that report no allocator stats (CPU CI)."""
    plan = _DEVICE_PLAN
    if plan is None:
        return None
    s = plan.spec("device_oom")
    if s is None:
        return None
    # one injection per activation window, not per read: device_memory()
    # runs on every scrape / bench meter / heal tick, and a read-rate
    # artifact would make injected[] counts incomparable across kinds
    if plan._oom_counted_epoch != plan.activations:
        plan._oom_counted_epoch = plan.activations
        plan._count("device_oom")
    return s.oom_ratio
