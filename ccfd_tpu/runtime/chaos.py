"""Seeded fault injection over a Supervisor: chaos testing for the pipeline.

The reference's failure story is entirely platform-delegated — k8s
``restartPolicy: Always`` and rolling strategies (SURVEY.md §5: "no
application-level retry/fault-injection in-tree"). This module makes the
recovery machinery *testable*: a ``ChaosMonkey`` kills a randomly chosen
supervised service on a seeded schedule, and the assertions that matter —
the supervisor restarts it, consumers resume from committed offsets, the
pipeline keeps scoring — run in CI (tests/test_chaos.py) instead of being
discovered in production.

Beyond whole-service kills, the monkey also drives **network fault
storms** (round 6): handed a ``FaultPlan`` (runtime/faults.py) it toggles
the plan active for ``fault_duration_s`` every ``fault_interval_s`` — a
window where every edge the plan names runs degraded (slow, flaky,
partitioned) — which is what exercises the circuit breakers and the
router's degradation ladder rather than the crash-restart machinery.

Determinism: victim choice and kill times derive from ``seed``, so a chaos
run is replayable. Every injection lands in ``history`` and, when a
registry is given, in ``chaos_injections_total{service=...}``; fault
windows land in ``fault_windows`` and ``chaos_fault_windows_total``.
"""

from __future__ import annotations

import random
import threading
import time

from ccfd_tpu.metrics.prom import Registry
from ccfd_tpu.runtime.supervisor import ServiceState, Supervisor


class ChaosMonkey:
    def __init__(
        self,
        supervisor: Supervisor,
        interval_s: float = 5.0,
        seed: int = 0,
        targets: list[str] | None = None,
        registry: Registry | None = None,
        fault_plan=None,
        device_fault_plan=None,
        storage_fault_plan=None,
        fault_interval_s: float | None = None,
        fault_duration_s: float = 2.0,
    ):
        self._sup = supervisor
        self.interval_s = interval_s
        self._rng = random.Random(seed)
        self._targets = list(targets) if targets is not None else None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.history: list[tuple[float, str]] = []  # (monotonic time, service)
        # fault storms: edge plan (runtime/faults.FaultPlan), device
        # plan (runtime/faults.DeviceFaultPlan) and/or storage plan
        # (runtime/faults.StorageFaultPlan) — all share one activation
        # surface. Storm-driven plans should be built active=False; the
        # monkey owns their duty cycle and toggles all in lockstep
        self._fault_plan = fault_plan
        self._device_fault_plan = device_fault_plan
        self._storage_fault_plan = storage_fault_plan
        self.fault_interval_s = fault_interval_s
        self.fault_duration_s = fault_duration_s
        self._fault_thread: threading.Thread | None = None
        self.fault_windows: list[tuple[float, float]] = []  # (start, end)
        self._c_injected = None
        self._c_fault_windows = None
        if registry is not None:
            self._c_injected = registry.counter(
                "chaos_injections_total", "injected service failures"
            )
            if (fault_plan is not None or device_fault_plan is not None
                    or storage_fault_plan is not None):
                self._c_fault_windows = registry.counter(
                    "chaos_fault_windows_total",
                    "fault-storm windows driven by the monkey",
                )

    def _eligible(self) -> list[str]:
        status = self._sup.status()
        names = self._targets if self._targets is not None else sorted(status)
        return [
            n
            for n in names
            if status.get(n, {}).get("state") == ServiceState.RUNNING.value
            # a Never-policy service (one-shot jobs like the producer)
            # can't be restarted: injecting there doesn't test recovery,
            # it just marks a healthy run FAILED and wedges readiness
            and status.get(n, {}).get("policy") != "Never"
        ]

    def kill_one(self) -> str | None:
        """Inject one failure now; returns the victim's name (or None if
        nothing was RUNNING to kill)."""
        victims = self._eligible()
        if not victims:
            return None
        name = self._rng.choice(victims)
        if not self._sup.inject_failure(name, reason="chaos-monkey"):
            return None
        self.history.append((time.monotonic(), name))
        if self._c_injected is not None:
            self._c_injected.inc(labels={"service": name})
        return name

    def fault_storm(self, duration_s: float | None = None) -> None:
        """Run one fault window now: activate the plan(s), hold for the
        duration (interruptible by stop), deactivate."""
        plans = [p for p in (self._fault_plan, self._device_fault_plan,
                              self._storage_fault_plan)
                 if p is not None]
        if not plans:
            return
        dur = self.fault_duration_s if duration_s is None else duration_s
        t0 = time.monotonic()
        for p in plans:
            p.activate()
        if self._c_fault_windows is not None:
            self._c_fault_windows.inc()
        try:
            self._stop.wait(dur)
        finally:
            for p in plans:
                p.deactivate()
            self.fault_windows.append((t0, time.monotonic()))

    def run(self) -> None:
        while not self._stop.is_set():
            if self._stop.wait(self.interval_s):
                return
            self.kill_one()

    def _run_faults(self) -> None:
        while not self._stop.is_set():
            if self._stop.wait(self.fault_interval_s):
                return
            self.fault_storm()

    def start(self) -> "ChaosMonkey":
        # re-arm BEFORE the thread exists: clearing inside run() would
        # race a stop() issued right after start() and erase it — the
        # same rule ManagedService.reset codifies for supervised services
        self._stop.clear()
        self._thread = threading.Thread(
            target=self.run, daemon=True, name="ccfd-chaos"
        )
        self._thread.start()
        if ((self._fault_plan is not None
                or self._device_fault_plan is not None
                or self._storage_fault_plan is not None)
                and self.fault_interval_s):
            self._fault_thread = threading.Thread(
                target=self._run_faults, daemon=True, name="ccfd-chaos-net"
            )
            self._fault_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if self._fault_thread is not None:
            self._fault_thread.join(timeout=5.0)
            # a storm interrupted mid-window must not leave edges (or the
            # device seams) degraded
            for p in (self._fault_plan, self._device_fault_plan,
                      self._storage_fault_plan):
                if p is not None:
                    p.deactivate()
