"""Device self-healing: failure taxonomy, heal ladder, warm re-promotion.

Every resilience layer so far treats the accelerator as infrastructure
that either works or is someone else's problem: PR 1 hardened the RPC
edges around it, PR 6 bounded individual dispatches into it, PR 10
measured it. Nothing OWNS the device as a fallible component — detects
that it wedged / OOM'd / fell into a compile storm, takes it out of
rotation, heals it, and returns traffic safely. That gap is ROADMAP
item 1's operational blocker (every bench capture since 2026-07-30 runs
``cpu (fallback: accelerator probe failed)``), and it is the layer the
serving literature presupposes: InferLine's planner retunes over hardware
it assumes stays healthy, and the "300M predictions/sec" utilization
story needs chips that stay IN rotation.

:class:`DeviceSupervisor` is that owner — a health state machine per
device::

    HEALTHY ──strike──▶ SUSPECT ──strikes──▶ QUARANTINED
       ▲                   │ (signals clear)        │ heal ladder:
       │                   ▼                        │  1. canary retry
       └──────────────  HEALTHY                     │  2. backend reinit
       ▲                                            │  3. scorer respawn
       │      N canaries + score parity             ▼ (jittered backoff)
       └───────────────  PROBATION  ◀───── canary passes

driven by three signal families, all drillable on CPU CI through the
device-fault plan (``runtime/faults.py``):

- **canary dispatch** — one tiny precompiled executable through the real
  serving dispatch path, bounded by the PR 6 ``bounded_dispatch``
  watchdog (a hung canary is killed and counted, never stalls the
  supervisor);
- **device telemetry** (PR 10) — allocator ``bytes_in_use`` vs
  ``bytes_limit`` for OOM pressure, per-stage compile rates for compile
  storms, H2D staging-put failures;
- **scorer-edge breaker** — an OPEN breaker means live traffic already
  found the device sick.

On QUARANTINE the supervisor pins the router's PR 1 degradation ladder to
the host tier (rules-only stays the last resort below it): the router's
``heal_gate`` check sits ABOVE the breaker, so not even a half-open probe
leaks traffic to the sick device. It then walks the heal ladder with
jittered exponential backoff, and re-promotes only **warm**: the full
executable inventory precompiles under the ``heal.warm`` compile-stage
label (the row bucket ladder and the seq (L, B) grid alike — zero XLA
compiles on the serving hot path after the flip), then N consecutive
canaries plus a host-vs-device score-parity check must pass, with
hysteresis so a flapping device backs off harder each round instead of
thrashing serving. Every transition exports
``ccfd_device_health{device,state}``, and quarantine/re-promotion edges
dump FlightRecorder bundles (``reason=device_quarantine`` /
``device_repromote``) so each incident is post-mortem-able.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Any, Callable

import numpy as np

from ccfd_tpu.runtime.breaker import backoff_s

log = logging.getLogger(__name__)

# state machine values, "bigger is sicker" except PROBATION (recovering)
HEALTHY, SUSPECT, QUARANTINED, PROBATION = 0, 1, 2, 3
STATE_NAMES = {HEALTHY: "healthy", SUSPECT: "suspect",
               QUARANTINED: "quarantined", PROBATION: "probation"}

# heal-ladder rungs, walked in order (the last repeats until it works)
RUNGS = ("canary_retry", "reinit", "respawn")

# compile-stage labels that legitimately compile OUTSIDE the serving hot
# path: warmups, swap precompiles, and the heal ladder's own warm step.
# Everything else counting a compile while serving is a storm signal —
# and after a re-promotion flip it would mean the re-promotion was COLD.
NON_SERVING_COMPILE_STAGES = frozenset({
    "total", "heal.warm", "heal.canary", "scorer.warmup", "seq.warmup",
    "seq.swap", "fused.warm",
})


def default_device_label() -> str:
    """``platform:id`` of the first local device (the gauge label)."""
    try:
        import jax

        d = jax.local_devices()[0]
        return f"{d.platform}:{d.id}"
    except Exception:  # noqa: BLE001 - no backend is itself a device state
        return "device:0"


def mesh_domain_label(mesh: Any) -> str:
    """``mesh:<platform>x<n>`` — the health-domain label for a multi-chip
    SPMD serving mesh.

    **The mesh is ONE health domain.** Every sharded executable spans
    every mesh device (one SPMD program, one launch), so there is no
    per-chip traffic to steer away from a sick chip: a canary kill or an
    OOM signal "on device 3" still fails the whole dispatch, and
    quarantining chip 3 alone would leave executables that *require*
    chip 3 in rotation. The supervisor therefore quarantines the MESH
    TIER — the router ladder pins to the host tier for the whole heal
    cycle — and re-promotes the mesh as a unit after the warm gate
    (documented in ARCHITECTURE "Partitioning & multi-chip serving")."""
    try:
        platform = mesh.devices.flat[0].platform
        return f"mesh:{platform}x{int(mesh.size)}"
    except Exception:  # noqa: BLE001
        return "mesh:unknown"


class DeviceSupervisor:
    """Per-device health state machine + heal ladder; see the module
    docstring. Runs as a supervised service (``run``/``stop``/``reset``)
    under the operator's ``heal:`` component; ``tick()`` is the test and
    drill surface.

    The supervisor IS the router's ``heal_gate``: ``device_allowed()``
    answers False from the moment of quarantine until the warm
    re-promotion flip, which pins the degradation ladder to its host tier
    (rules-only as the last resort) for the whole heal cycle.
    """

    def __init__(
        self,
        scorer: Any,
        registry: Any = None,
        breaker: Any = None,
        telemetry: Any = None,
        profiler: Any = None,
        recorder: Any = None,
        overload: Any = None,
        device: str | None = None,
        canary_rows: int = 16,
        canary_deadline_ms: float = 250.0,
        suspect_strikes: int = 2,
        probation_canaries: int = 3,
        parity_tol: float = 0.05,
        oom_ratio: float = 0.92,
        compile_storm_per_s: float = 2.0,
        backoff_base_s: float = 0.5,
        backoff_cap_s: float = 30.0,
        flap_window_s: float = 60.0,
        reinit_fn: Callable[[], None] | None = None,
        respawn_fn: Callable[[], None] | None = None,
        seed: int = 0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.scorer = scorer
        self.breaker = breaker
        self.telemetry = telemetry
        self.profiler = profiler
        self.recorder = recorder
        self.overload = overload
        # health-domain resolution: a mesh-sharded scorer is supervised as
        # ONE domain (see mesh_domain_label — every SPMD executable spans
        # every mesh device, so quarantine/heal/re-promote act on the
        # mesh tier, never on an individual chip)
        scorer_mesh = getattr(scorer, "mesh", None)
        self.domain = "mesh" if scorer_mesh is not None else "device"
        if device is None:
            device = (mesh_domain_label(scorer_mesh)
                      if scorer_mesh is not None else default_device_label())
        self.device = device
        self.canary_deadline_s = max(1e-3, float(canary_deadline_ms) / 1e3)
        self.suspect_strikes = max(1, int(suspect_strikes))
        self.probation_canaries = max(1, int(probation_canaries))
        self.parity_tol = float(parity_tol)
        self.oom_ratio = float(oom_ratio)
        self.compile_storm_per_s = float(compile_storm_per_s)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.flap_window_s = float(flap_window_s)
        self._reinit_fn = reinit_fn
        self._respawn_fn = respawn_fn
        self._rng = random.Random(seed)
        self._clock = clock
        self._mu = threading.Lock()
        self._stop = threading.Event()

        # canary probe: real (seeded) rows, NOT zeros — the parity check
        # compares device vs host probabilities, and an all-zeros batch
        # collapses to one output value that can't catch a scrambled graph
        nf = int(getattr(scorer, "num_features", 30))
        rng = np.random.default_rng(seed)
        self._probe_x = rng.standard_normal(
            (max(1, int(canary_rows)), nf)).astype(np.float32)

        self._state = HEALTHY
        self._strikes = 0
        self._last_reasons: list[str] = []
        self._rung_idx = 0
        self._heal_attempt = 0       # backoff exponent within a quarantine
        self._next_heal_at = 0.0
        self._probation_passes = 0
        self._flap_streak = 0        # re-quarantines inside flap_window_s
        self._last_promote_at: float | None = None
        self._prev_compile: dict[str, int] = {}
        self._prev_compile_at: float | None = None
        # baseline diffed signals from their LIVE values: the supervisor
        # comes up after serving (operator step 7e), and history that
        # predates it must not read as first-tick strikes
        self._prev_put_failures = (telemetry.h2d_failures()
                                   if telemetry is not None else 0)
        self._prev_breaker_opens = (breaker.opens
                                    if breaker is not None else 0)
        # lifetime counters for drills/tests
        self.quarantines = 0
        self.repromotions = 0
        self.canary_failures = 0

        self._g_health = self._c_transitions = None
        self._c_attempts = self._c_canary = None
        if registry is not None:
            self._g_health = registry.gauge(
                "ccfd_device_health",
                "device health state one-hot: 1 on the current state's "
                "series, 0 elsewhere (healthy/suspect/quarantined/"
                "probation per device)",
            )
            self._c_transitions = registry.counter(
                "ccfd_heal_transitions_total",
                "device health state transitions by target state",
            )
            self._c_attempts = registry.counter(
                "ccfd_heal_attempts_total",
                "heal-ladder attempts by rung (canary_retry -> reinit -> "
                "respawn, jittered backoff between attempts)",
            )
            self._c_canary = registry.counter(
                "ccfd_heal_canary_total",
                "canary dispatch outcomes (pass / fail)",
            )
            self._export_state()

        self._own_dispatcher = None
        if overload is None:
            from ccfd_tpu.serving.dispatch import DeviceDispatcher

            self._own_dispatcher = DeviceDispatcher(
                max_threads=2, name="ccfd-heal-canary")

    # -- state surface ------------------------------------------------------
    @property
    def state(self) -> str:
        return STATE_NAMES[self._state]

    def device_allowed(self) -> bool:
        """The router ladder's gate: may live traffic touch the device?
        False from quarantine entry until the warm re-promotion flip —
        PROBATION still answers False (canaries + parity must pass before
        serving returns; that asymmetry is the hysteresis)."""
        return self._state in (HEALTHY, SUSPECT)

    def _export_state(self) -> None:
        if self._g_health is None:
            return
        for s, name in STATE_NAMES.items():
            self._g_health.set(
                1.0 if s == self._state else 0.0,
                labels={"device": self.device, "state": name})

    def _set_state(self, state: int) -> None:
        if state == self._state:
            return
        log.info("device %s: %s -> %s", self.device,
                 STATE_NAMES[self._state], STATE_NAMES[state])
        self._state = state
        self._export_state()
        if self._c_transitions is not None:
            self._c_transitions.inc(labels={"to": STATE_NAMES[state]})

    # -- canary -------------------------------------------------------------
    def _device_dispatch(self) -> np.ndarray:
        """One tiny dispatch through the real serving path — PRECOMPILED
        (the probe rides the smallest warmed bucket), so the canary
        measures the device, not an XLA compile. Any compile it DOES pay
        (the retry after a cache-clearing reinit rung) bills to
        ``heal.canary`` — the label is set here, on whichever sacrificial
        thread actually runs the dispatch, because the compile-stage
        contextvar does not cross the watchdog's thread boundary."""
        from ccfd_tpu.observability.profile import compile_stage

        scorer = self.scorer
        with compile_stage("heal.canary"):
            pipelined = getattr(scorer, "score_pipelined", None)
            if callable(pipelined):
                # the row Scorer: score_pipelined is the pure device path
                # (score() would take the host tier at canary batch sizes)
                return np.asarray(pipelined(self._probe_x, depth=1))
            return np.asarray(scorer.score(self._probe_x))

    def _run_canary(self, parity: bool = False) -> tuple[bool, str]:
        """(passed, reason). Bounded by the PR 6 watchdog; with
        ``parity`` the device output must also agree with the host
        forward within ``parity_tol`` (the re-promotion gate's proof the
        healed device computes the same model, not just answers)."""
        try:
            if self.overload is not None:
                out = self.overload.bounded_dispatch(
                    self._device_dispatch, deadline_s=self.canary_deadline_s)
            else:
                out = self._own_dispatcher.call(
                    self._device_dispatch, self.canary_deadline_s)
        except Exception as e:  # noqa: BLE001 - every failure mode counts
            self.canary_failures += 1
            if self._c_canary is not None:
                self._c_canary.inc(labels={"outcome": "fail"})
            return False, f"canary: {type(e).__name__}: {e}"
        out = np.asarray(out)
        if out.shape != (len(self._probe_x),) or not np.isfinite(out).all():
            self.canary_failures += 1
            if self._c_canary is not None:
                self._c_canary.inc(labels={"outcome": "fail"})
            return False, "canary: invalid response shape/values"
        if parity and getattr(self.scorer, "has_host_forward", False):
            host = np.asarray(self.scorer.host_score(self._probe_x))
            delta = float(np.max(np.abs(out - host)))
            if delta > self.parity_tol:
                self.canary_failures += 1
                if self._c_canary is not None:
                    self._c_canary.inc(labels={"outcome": "fail"})
                return False, f"parity: max |device-host| {delta:.4f}"
        if self._c_canary is not None:
            self._c_canary.inc(labels={"outcome": "pass"})
        return True, ""

    # -- telemetry signals --------------------------------------------------
    def _collect_signals(self) -> list[str]:
        """Quarantine evidence from the PR 10 planes; each entry is one
        strike-worthy reason."""
        reasons: list[str] = []
        tele = self.telemetry
        if tele is not None:
            try:
                for dev, kinds in tele.device_memory().items():
                    used, limit = kinds.get("bytes_in_use"), kinds.get(
                        "bytes_limit")
                    if used and limit and used / limit >= self.oom_ratio:
                        reasons.append(
                            f"device_oom: {dev} {used}/{limit} "
                            f">= {self.oom_ratio:.2f}")
                        break
            except Exception:  # noqa: BLE001 - telemetry must not crash heal
                pass
            failures = tele.h2d_failures()
            if failures > self._prev_put_failures:
                reasons.append(
                    f"put_fail: {failures - self._prev_put_failures} "
                    "staging failures since last tick")
            self._prev_put_failures = failures
        prof = self.profiler
        if prof is not None:
            now = self._clock()
            counts = prof.compile_counts()
            if self._prev_compile_at is not None:
                dt = max(1e-6, now - self._prev_compile_at)
                serving = sum(
                    counts.get(s, 0) - self._prev_compile.get(s, 0)
                    for s in counts
                    if s not in NON_SERVING_COMPILE_STAGES)
                if serving / dt >= self.compile_storm_per_s:
                    reasons.append(
                        f"compile_storm: {serving} serving-stage compiles "
                        f"in {dt:.1f}s")
            self._prev_compile = counts
            self._prev_compile_at = now
        br = self.breaker
        if br is not None:
            opens = br.opens
            if br.state == "open" or opens > self._prev_breaker_opens:
                reasons.append("breaker: scorer edge open/tripped")
            self._prev_breaker_opens = opens
        return reasons

    # -- transitions --------------------------------------------------------
    def _quarantine(self, reasons: list[str]) -> None:
        self.quarantines += 1
        self._last_reasons = reasons[:8]
        now = self._clock()
        if self._state in (QUARANTINED, PROBATION):
            # re-quarantined MID-heal (warm step or probation canary
            # failed): that is a failed ladder attempt, so escalate the
            # rung and deepen the backoff — resetting here would loop a
            # canary-pass/warm-fail device at rung 0 forever, never
            # reaching the reinit/respawn rungs that could actually fix
            # it (no promotion happened, so the flap streak stays put)
            self._rung_idx += 1
            self._heal_attempt += 1
        else:
            # flap hysteresis: a device re-quarantined shortly after a
            # re-promotion earns a harder backoff each round, so a
            # flapping attachment cannot thrash serving at the heal
            # ladder's base rate
            if (self._last_promote_at is not None
                    and now - self._last_promote_at <= self.flap_window_s):
                self._flap_streak += 1
            else:
                self._flap_streak = 0
            self._rung_idx = 0
            self._heal_attempt = self._flap_streak
        self._next_heal_at = now + backoff_s(
            self._heal_attempt, self.backoff_base_s, self.backoff_cap_s,
            self._rng)
        self._set_state(QUARANTINED)
        log.warning("device %s QUARANTINED: %s", self.device, reasons)
        if self.recorder is not None:
            try:
                self.recorder.incident({
                    "type": "device_quarantine",
                    "device": self.device,
                    "signals": self._last_reasons,
                })
            except Exception:  # noqa: BLE001 - evidence, not control flow
                pass

    def _heal_step(self) -> None:
        """One heal-ladder attempt, backoff-gated. Escalates one rung per
        failure; the last rung (respawn) repeats until it works."""
        now = self._clock()
        if now < self._next_heal_at:
            return
        rung = RUNGS[min(self._rung_idx, len(RUNGS) - 1)]
        if self._c_attempts is not None:
            self._c_attempts.inc(labels={"rung": rung})
        try:
            if rung == "reinit":
                self._reinit()
            elif rung == "respawn":
                self._respawn()
        except Exception as e:  # noqa: BLE001 - a failed rung is a failed
            log.warning("heal rung %s raised: %r", rung, e)  # attempt
            self._escalate(now)
            return
        ok, reason = self._run_canary()
        if ok:
            self._enter_probation()
            return
        log.info("heal rung %s: canary still failing (%s)", rung, reason)
        self._escalate(now)

    def _escalate(self, now: float) -> None:
        self._rung_idx += 1
        self._heal_attempt += 1
        self._next_heal_at = now + backoff_s(
            self._heal_attempt, self.backoff_base_s, self.backoff_cap_s,
            self._rng)

    def _reinit(self) -> None:
        """Rung 2: backend re-probe/reinit. The default drops every jax
        compilation cache entry and live trace state the wedge might have
        poisoned; the warm step recompiles the inventory BEFORE serving
        returns, so this never moves compile cost onto the hot path."""
        if self._reinit_fn is not None:
            self._reinit_fn()
            return
        import jax

        jax.clear_caches()

    def _respawn(self) -> None:
        """Rung 3: supervised scorer respawn with checkpoint restore. The
        operator wires the lifecycle controller's champion-checkpoint
        restore here; the default re-publishes the scorer's own params
        through ``swap_params`` — fresh device buffers for every tree
        (a device-side state scrub even without a lifecycle)."""
        if self._respawn_fn is not None:
            self._respawn_fn()
            return
        import jax

        params = jax.tree.map(np.asarray, self.scorer.params)
        self.scorer.swap_params(params)

    def _enter_probation(self) -> None:
        self._probation_passes = 0
        self._set_state(PROBATION)
        self._warm()

    def _warm(self) -> None:
        """Precompile the full executable inventory (the row bucket
        ladder / the seq (L, B) grid — whatever ``warmup`` covers) under
        the ``heal.warm`` compile-stage label. This is what makes the
        re-promotion WARM: every compile bills here, and the drills
        assert zero serving-stage compiles after the flip."""
        from ccfd_tpu.observability.profile import compile_stage

        try:
            with compile_stage("heal.warm"):
                self.scorer.warmup()
        except Exception as e:  # noqa: BLE001 - a failed warm is a failed
            log.warning("heal warm step failed: %r", e)  # probation
            self._quarantine([f"warm: {type(e).__name__}: {e}"])

    def _probation_step(self) -> None:
        ok, reason = self._run_canary(parity=True)
        if not ok:
            log.warning("probation canary failed (%s); re-quarantining",
                        reason)
            self._quarantine([f"probation: {reason}"])
            return
        self._probation_passes += 1
        if self._probation_passes < self.probation_canaries:
            return
        # warm re-promotion flip: serving returns to the device
        self._last_promote_at = self._clock()
        self.repromotions += 1
        # re-baseline every diffed signal at the flip: the quarantine era
        # legitimately produced compiles (a reinit rung clears the jax
        # caches; its canary recompiles untagged), put failures and
        # breaker trips — diffing the first healthy tick against the
        # PRE-quarantine baseline would read that history as fresh
        # evidence and re-quarantine a healed device
        if self.profiler is not None:
            self._prev_compile = self.profiler.compile_counts()
            self._prev_compile_at = self._clock()
        if self.telemetry is not None:
            self._prev_put_failures = self.telemetry.h2d_failures()
        if self.breaker is not None:
            self._prev_breaker_opens = self.breaker.opens
        if self.breaker is not None:
            # the breaker's window is full of quarantine-era failures,
            # and from OPEN record_success() is a state no-op: a residual
            # cooldown (consecutive_opens backoff can reach tens of
            # seconds) would keep refusing the healed device AND read as
            # fresh quarantine evidence next tick. The probation gate (N
            # canaries + parity) outranks a half-open probe, so close the
            # scorer edge outright.
            try:
                close = getattr(self.breaker, "force_close", None)
                if callable(close):
                    close()
                else:
                    self.breaker.record_success()
            except Exception:  # noqa: BLE001
                pass
        self._strikes = 0
        self._set_state(HEALTHY)
        log.info("device %s re-promoted (warm) after %d canaries",
                 self.device, self._probation_passes)
        if self.recorder is not None:
            try:
                self.recorder.incident({
                    "type": "device_repromote",
                    "device": self.device,
                    "canaries": self._probation_passes,
                })
            except Exception:  # noqa: BLE001
                pass

    # -- the supervised tick ------------------------------------------------
    def tick(self) -> str:
        """One supervision cycle; returns the (possibly new) state name."""
        with self._mu:
            state = self._state
            if state in (HEALTHY, SUSPECT):
                reasons = self._collect_signals()
                ok, reason = self._run_canary()
                if not ok:
                    reasons.append(reason)
                if reasons:
                    self._strikes += 1
                    self._last_reasons = reasons[:8]
                    if self._strikes >= self.suspect_strikes:
                        self._quarantine(reasons)
                    else:
                        self._set_state(SUSPECT)
                else:
                    self._strikes = 0
                    if state == SUSPECT:
                        self._set_state(HEALTHY)
            elif state == QUARANTINED:
                self._heal_step()
            elif state == PROBATION:
                self._probation_step()
            return STATE_NAMES[self._state]

    def status(self) -> dict[str, Any]:
        with self._mu:
            return {
                "device": self.device,
                "domain": self.domain,
                "state": STATE_NAMES[self._state],
                "strikes": self._strikes,
                "reasons": list(self._last_reasons),
                "rung": RUNGS[min(self._rung_idx, len(RUNGS) - 1)],
                "quarantines": self.quarantines,
                "repromotions": self.repromotions,
                "canary_failures": self.canary_failures,
                "flap_streak": self._flap_streak,
            }

    # -- supervised-service surface ----------------------------------------
    def reset(self) -> None:
        self._stop.clear()

    def stop(self) -> None:
        self._stop.set()

    def run(self, interval_s: float = 5.0) -> None:
        while not self._stop.wait(interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - one bad tick must not kill
                log.exception("heal tick failed")  # the supervision loop
