"""Crash recovery for the stateful pipeline tier: engine snapshot +
bus-offset replay as one consistent cut.

The reference's process engine (KIE server, reference deploy/
ccd-service.yaml:1-124) keeps persistent process state in a database and
relies on Kafka redelivery after a pod restart.  This module is that
capability for the in-process runtime: a ``CheckpointCoordinator``
periodically captures

  - the engine's snapshot (process/engine.py snapshot(): active instances,
    open tasks, id counters, timer remainders), and
  - the committed offsets of every consumer group whose records mutate
    engine state (the router's transaction group and its customer-response
    signal group),

taken *at a batch boundary* — the router's pause() barrier guarantees no
consumed-but-unrouted records exist when the cut is read (a Flink-style
aligned checkpoint with one source).  After an engine crash, ``restore()``
builds a fresh engine from the registered definitions, loads the last
snapshot, rewinds the groups to the cut (Broker.reset_offsets — live
consumers follow, they hold no position of their own), and swaps the new
engine into the router.

Semantics are at-least-once, like Kafka redelivery into a restarted KIE
pod before its DB transaction committed: work the dead engine did after
the last cut is rolled back and re-driven from the bus.  Process ids
restart from the snapshot's ``next_pid``, so starts the dead engine
emitted after the cut are void — the coordinator writes an
``engine_restored`` marker event (with that ``next_pid``) into the audit
topic, which is exactly the information an audit consumer needs to
reconcile: any ``process_started`` before the marker with
``pid >= next_pid`` was rolled back and will be re-driven (possibly
reusing the pid).  tools/chaos_soak.py asserts this accounting under a
ChaosMonkey that kills the engine mid-load.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable

from ccfd_tpu.process.engine import Engine


def _np_jsonable(obj: Any) -> Any:
    """json.dumps default for cut contents: numpy arrays/scalars (extra-
    state snapshots return them raw to keep the barrier short)."""
    if hasattr(obj, "tolist"):
        return obj.tolist()
    raise TypeError(f"not JSON-serializable: {type(obj).__name__}")


class CheckpointCoordinator:
    """Aligned checkpoints + crash restore for one router/engine pair.

    ``engine_factory`` must return a fresh, fully ``register``-ed engine
    wired to the same audit sink (definitions are code, not data —
    process/engine.py restore()).
    """

    def __init__(
        self,
        router,                    # router.Router (pause/resume/swap_engine)
        broker,                    # bus.broker.Broker
        engine_factory: Callable[[], Engine],
        interval_s: float = 5.0,
        pause_timeout_s: float = 10.0,
        on_swap: Callable[[Engine], None] | None = None,
        path: str | None = None,
        retain: int | None = None,
    ):
        self.router = router
        self.broker = broker
        self.engine_factory = engine_factory
        self.interval_s = interval_s
        self.pause_timeout_s = pause_timeout_s
        # other holders of an engine reference (the KIE-shaped REST
        # server, the platform object) re-point here, inside the barrier
        self.on_swap = on_swap
        cfg = router.cfg
        # every (group, topic) whose consumption mutates engine state
        self._cut_groups = (
            ("router", cfg.kafka_topic),
            ("router-responses", cfg.customer_response_topic),
        )
        self._audit_topic = cfg.audit_topic
        # cut durability: with ``path`` set, every validated cut lands on
        # disk (tmp+rename), so a FULL-process crash recovers via
        # restore_from_disk() at the next bring-up — paired with a
        # durable bus (log_dir), that is the complete crash story:
        # engine state from the cut, the gap re-driven from the log
        self.path = path
        # generations of the cut retained on disk (runtime/durability.py):
        # a torn/bit-flipped newest cut falls back to the previous one —
        # a crash a few seconds earlier — instead of a cold start
        self.retain = retain
        if path:
            import os

            from ccfd_tpu.runtime.durability import sweep_tmp

            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            sweep_tmp(os.path.dirname(os.path.abspath(path)))
        self._io_lock = threading.Lock()  # orders cut writes off _lock
        # additional PIPELINE STATE that must ride the cut: anything a
        # rewound record replay would otherwise double-apply — e.g. the
        # seq scorer's per-customer histories (serving/history.py).
        # name -> (snapshot_fn() -> JSONable, restore_fn(snap) -> None)
        self._extra_state: dict[str, tuple[Callable[[], Any],
                                           Callable[[Any], None]]] = {}
        self._last: dict[str, Any] | None = None  # {"snap","offsets","ts"}
        self._lock = threading.Lock()  # serializes checkpoint vs restore
        # Seed the retention pin NOW, at the groups' current committed
        # positions (<= any future cut): the FIRST checkpoint has no prior
        # pin, so in the window between its barrier release and its own
        # pin write (snapshot JSON-normalization + disk persistence) the
        # consuming groups race ahead and retention could trim
        # [first cut, live position) — exactly the records that cut's
        # restore would replay (ADVICE r5 medium). On a crash bring-up
        # the groups' replayed positions sit PAST the persisted cut the
        # upcoming restore_from_disk() will rewind to, so the seed folds
        # in the on-disk cut's offsets (element-wise min) — overwriting
        # the surviving durable pin with crash-time positions would
        # un-protect exactly the replay window the pin existed to keep.
        # Best-effort: transports that cannot report offsets at bring-up
        # just skip the seed.
        try:
            seed = {
                f"{g}\x00{t}": [int(o)
                                for o in broker.committed_offsets(g, t)]
                for g, t in self._cut_groups
            }
            for key, offs in self._peek_disk_cut_offsets().items():
                cur = seed.get(key)
                seed[key] = (list(offs) if cur is None
                             else [min(a, b) for a, b in zip(cur, offs)])
            self._pin_retention(seed)
        except Exception:  # noqa: BLE001 - seeding is protective only
            import logging

            logging.getLogger(__name__).exception(
                "retention pin seed at coordinator start failed")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.checkpoints = 0
        self.restores = 0
        self.skipped = 0
        self.unacked_restores = 0  # barrier timeout (e.g. wedged scorer):
        # restore proceeded anyway — safe, because the shut-down engine
        # refuses the late in-flight batch (Engine._check_alive) and
        # generation-guarded state (HistoryStore) drops late commits

    def register_state(self, name: str, snapshot_fn: Callable[[], Any],
                       restore_fn: Callable[[Any], None]) -> None:
        """Attach extra pipeline state to every cut. ``snapshot_fn`` runs
        under the barrier — keep it COPY-ONLY (return numpy arrays as-is;
        the coordinator converts to JSON outside the barrier); a
        ``restore_fn`` runs during restore after the engine swap, with
        ``None`` meaning reset-to-empty. State registered after
        checkpoints were already taken starts riding the NEXT cut."""
        self._extra_state[name] = (snapshot_fn, restore_fn)

    # -- checkpoint --------------------------------------------------------
    def checkpoint(self) -> dict[str, Any] | None:
        """One aligned checkpoint; None if the barrier wasn't acked (router
        mid-restart — state is then mutating unpredictably, skip rather
        than record a torn cut)."""
        import json

        with self._lock:
            acked = self.router.pause(self.pause_timeout_s)
            try:
                if not acked and self._router_loop_alive():
                    self.skipped += 1
                    return None
                # barrier holds (or no loop is running to mutate state).
                # validate=False: the JSON round-trip is ~70% of a large
                # snapshot and belongs OUTSIDE the barrier — the copy is
                # already detached, the pipeline should be flowing again
                cut = {
                    "snap": self.router.engine.snapshot(validate=False),
                    "offsets": {
                        f"{g}\x00{t}": self.broker.committed_offsets(g, t)
                        for g, t in self._cut_groups
                    },
                    "extra": {
                        name: snap_fn()
                        for name, (snap_fn, _) in self._extra_state.items()
                    },
                    "ts": time.time(),
                }
            finally:
                self.router.resume()
            # whole-cut JSON normalization OUTSIDE the barrier (snapshot
            # fns return raw numpy for speed under the pause; the
            # conversion cost lands here, where the pipeline is flowing)
            cut = json.loads(json.dumps(cut, default=_np_jsonable))
            self._last = cut
            self.checkpoints += 1
        # disk persistence OFF the coordinator lock: a crash restore must
        # not wait behind a large snapshot's serialize+write. _io_lock
        # alone orders writers; a slightly stale cut on disk is exactly
        # as recoverable as a crash a moment earlier.
        wrote = True
        if self.path:
            from ccfd_tpu.runtime.durability import write_json_artifact

            with self._io_lock:
                # checksummed + fsynced + atomic with generation retention
                # (a failed write keeps the previous cut — exactly as
                # recoverable as a crash one interval earlier)
                wrote = write_json_artifact(self.path, {"version": 1, **cut},
                                            artifact="recovery_cut",
                                            retain=self.retain)
        # Pin retention only AFTER the cut is durable: until the atomic
        # replace lands, the newest cut a cold start can load is the
        # PREVIOUS one, and the previous pin is what keeps that cut's
        # replay records alive. Pinning first would let retention trim
        # [old cut, new cut) while disk still holds the old cut — a crash
        # in that window would restore a cut whose records are gone. The
        # same invariant on a FAILED durable write (full disk, injected
        # storage fault — write_json_artifact is best-effort): the newest
        # cut on disk is still the previous one, so the previous pin must
        # stand; advancing it would un-protect exactly the replay window
        # that cut needs.
        if wrote:
            self._pin_retention(cut["offsets"])
        return cut

    def _peek_disk_cut_offsets(self) -> dict[str, list[int]]:
        """The persisted cut's offsets map, for the constructor's pin
        seed — {} when there is no (usable) cut on disk. Deliberately
        tolerant: a corrupt file reads as no-cut here exactly as it does
        in restore_from_disk()."""
        from ccfd_tpu.runtime.durability import read_json_artifact

        if not self.path:
            return {}
        try:
            # quarantine=False: the peek must not mutate disk state the
            # upcoming restore_from_disk() will judge for itself
            cut = read_json_artifact(self.path, artifact="recovery_cut",
                                     fallback=True, quarantine=False)
            offsets = cut["offsets"] if cut.get("version") == 1 else {}
            return {
                k: [int(o) for o in v]
                for k, v in offsets.items()
                if isinstance(v, list)
            }
        except Exception:  # noqa: BLE001 - a corrupt/missing file reads
            # as no-cut here exactly as it does in restore_from_disk()
            return {}

    def _pin_retention(self, cut_offsets: dict[str, list[int]]) -> None:
        """Publish the cut as a committed position under the broker's
        retention pin group: the broker's delete-before-committed-offset
        retention (bus/broker.py) then cannot delete any record a restore
        of THIS cut would replay. Per topic the pin is the element-wise
        min across the cut's groups — the earliest position any rewind
        could aim at. An in-process Broker without retention just records
        a harmless extra group. The pin IS sent over every transport with
        an offset-admin surface — RemoteBroker forwards it to the bus
        server, whose broker-side retention honors it exactly like the
        in-process case, and KafkaAdapter commits it as ordinary group
        offsets — but on REAL Kafka, size/time retention ignores consumer
        positions entirely, so the pin does NOT block broker-side
        deletion there: it only documents the cut for operators
        (``kafka-consumer-groups --describe``), and recovery over a real
        cluster relies on the cluster's retention window being wider than
        the checkpoint interval. Only a transport with no
        ``reset_offsets`` at all is skipped."""
        from ccfd_tpu.bus.broker import RETENTION_PIN_GROUP

        if not callable(getattr(self.broker, "reset_offsets", None)):
            return
        pin: dict[str, list[int]] = {}
        for key, offs in cut_offsets.items():
            _, t = key.split("\x00", 1)
            cur = pin.get(t)
            pin[t] = (list(offs) if cur is None
                      else [min(a, b) for a, b in zip(cur, offs)])
        for t, offs in pin.items():
            try:
                self.broker.reset_offsets(RETENTION_PIN_GROUP, t, offs)
            except Exception:  # noqa: BLE001 - pinning is protective only;
                # a transport that rejects it must not fail the checkpoint
                import logging

                logging.getLogger(__name__).exception(
                    "retention pin failed for %r", t)

    def _router_loop_alive(self) -> bool:
        """Best effort: is some thread inside the router's run loop?  The
        stop flag is the only observable; a cleared stop flag with no ack
        means a live loop that didn't reach the barrier."""
        return not self.router._stop.is_set()

    def start(self) -> "CheckpointCoordinator":
        """Periodic checkpoints on a daemon thread."""
        def loop() -> None:
            while not self._stop.wait(self.interval_s):
                try:
                    self.checkpoint()
                except Exception:  # noqa: BLE001 - keep checkpointing
                    import logging

                    logging.getLogger(__name__).exception("checkpoint failed")
        self._thread = threading.Thread(
            target=loop, daemon=True, name="ccfd-checkpoint"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # -- restore -----------------------------------------------------------
    def restore(self, reason: str = "crash", boot: bool = False) -> Engine:
        """Rebuild the engine from the last cut and rewind the bus to it.

        Safe to call from the supervisor's reset hook while the router is
        still polling: the router is paused across the swap (its in-flight
        batch drains into the doomed engine first — those starts are void,
        their records re-deliver after the rewind).  With no checkpoint
        yet, recovery is from genesis: empty engine, offsets 0 — the full
        at-least-once replay of the durable log.

        ``boot=True`` (restore_from_disk at bring-up, before any service
        thread exists): there is no loop to ack the barrier — waiting the
        pause timeout would just stall bring-up — and recycling the
        consumers is unconditionally safe."""
        with self._lock:
            acked = self.router.pause(0.0 if boot else self.pause_timeout_s)
            if not acked and not boot and self._router_loop_alive():
                # only a LIVE loop missing the barrier is notable; a
                # stopped router has nothing to ack
                self.unacked_restores += 1
            try:
                # silence the doomed engine FIRST: its scheduled timers
                # must not fire into dead state or emit post-marker audit
                # events through the shared sink (Engine.shutdown)
                old = self.router.engine
                if hasattr(old, "shutdown"):
                    old.shutdown()
                if self._last is not None:
                    offsets = self._last["offsets"]
                    next_pid = self._last["snap"]["next_pid"]
                    active_pids = [
                        i["pid"] for i in self._last["snap"]["instances"]
                        if i["status"] == "active"
                    ]
                else:
                    offsets = {
                        f"{g}\x00{t}": [0] * len(
                            self.broker.committed_offsets(g, t)
                        )
                        for g, t in self._cut_groups
                    }
                    next_pid = 1
                    active_pids = []
                if self._audit_topic:
                    # The marker goes in BEFORE the replacement engine is
                    # even built: Engine.restore() re-arms overdue timers
                    # with zero delay, so the new engine can emit its
                    # first events the instant restore() releases its
                    # lock — those must land after the epoch boundary.
                    # (The old engine is already silenced, so nothing
                    # else can write in between.)
                    # One marker PER PARTITION: audit events are keyed by
                    # pid (partition-sticky), so each partition's offset
                    # order is the ground truth — a consumer of any single
                    # partition must see the boundary in-stream
                    # (timestamps can collide within a batch flush and
                    # cannot order events across it).
                    # ``active_pids`` is the restored-active set: events
                    # the dead epoch emitted past the cut for THESE pids
                    # (e.g. a timer completion) are rolled back too — the
                    # restored instance is live again and may re-complete.
                    # An audit consumer needs exactly {next_pid,
                    # active_pids} to reconcile at-least-once redelivery.
                    marker = {
                        "event": "engine_restored",
                        "reason": reason,
                        "next_pid": next_pid,
                        "active_pids": active_pids,
                        "ts": time.time(),
                    }
                    n_parts = len(self.broker.end_offsets(self._audit_topic))
                    for p in range(n_parts):
                        self.broker.produce(self._audit_topic, marker,
                                            partition=p)
                engine = self.engine_factory()
                if self._last is not None:
                    engine.restore(self._last["snap"])
                # swap BEFORE the rewind: if the pause wasn't acked (router
                # wedged past the timeout, still looping), a post-rewind
                # poll would commit the rewound records forward and feed
                # them to the shut-down engine — permanently lost. Swapped
                # first, the worst case is a pre-rewind batch landing in
                # the NEW engine and then re-delivering after the rewind:
                # duplicates, which is what at-least-once already means.
                self.router.swap_engine(engine)
                if self.on_swap is not None:
                    self.on_swap(engine)
                # extra pipeline state resets to the cut too — replayed
                # records then re-apply onto exactly the state they
                # already applied to once (e.g. per-customer histories;
                # without this, replay double-appends). Absent entries
                # (state registered after the cut, or genesis) reset via
                # restore_fn(None) semantics only when recorded.
                extra = (self._last.get("extra", {})
                         if self._last is not None else {})
                for name, (_, restore_fn) in self._extra_state.items():
                    try:
                        # None = reset-to-empty (genesis restore, or state
                        # registered after the recorded cut): replay from
                        # the rewound offsets rebuilds it from scratch
                        restore_fn(extra.get(name))
                    except Exception:  # noqa: BLE001 - a state module's
                        # failure must not abort the engine restore
                        import logging

                        logging.getLogger(__name__).exception(
                            "extra state %r restore failed", name
                        )
                if boot or acked or not self._router_loop_alive():
                    # real Kafka refuses offset resets for a group with
                    # live members: the parked loop's consumers still
                    # heartbeat, so they are closed and recreated before
                    # the rewind (in-process: a cheap rebalance). Only
                    # safe when the loop is provably parked, dead, or not
                    # yet born — an unacked live loop could be mid-poll.
                    self.router.recycle_consumers()
                for key, offs in offsets.items():
                    g, t = key.split("\x00", 1)
                    self.broker.reset_offsets(g, t, offs)
            finally:
                self.router.resume()
            self.restores += 1
            return engine


    # -- full-process crash recovery ---------------------------------------
    def restore_from_disk(self, reason: str = "boot") -> Engine | None:
        """Recover from the on-disk cut at bring-up, BEFORE the router's
        loop starts: loads the last persisted checkpoint, restores it into
        a fresh engine, rewinds the bus groups to the cut, and swaps it in
        — the same restore path a live crash takes, minus a barrier to
        wait for. Returns the restored engine, or None when no usable cut
        exists (missing/corrupt file reads as a cold start, never a
        crash)."""
        from ccfd_tpu.runtime.durability import read_json_artifact

        if not self.path:
            return None
        try:
            # verified read (runtime/durability.py): a torn/bit-flipped
            # newest cut is QUARANTINED and the last-good retained
            # generation restores instead — replay from a slightly older
            # cut beats both a crash and a cold start
            cut = read_json_artifact(self.path, artifact="recovery_cut",
                                     fallback=True)
            # valid JSON is not necessarily a valid cut: guard the shape,
            # not just the parse (null / [] / non-dict snap must all read
            # as cold starts)
            if not isinstance(cut, dict) or cut.get("version") != 1:
                raise ValueError(f"not a v1 cut: {type(cut).__name__}")
            last = {"snap": cut["snap"], "offsets": cut["offsets"],
                    "ts": cut.get("ts", 0.0)}
            if not isinstance(last["snap"], dict) or not isinstance(
                    last["offsets"], dict):
                raise ValueError("cut fields have wrong shapes")
        except FileNotFoundError:
            return None  # cold start, nothing ever written
        except Exception as e:  # noqa: BLE001 - includes CorruptArtifact
            import logging

            logging.getLogger(__name__).warning(
                "checkpoint file %s unusable (%s); cold start", self.path, e
            )
            return None
        self._last = last
        return self.restore(reason=reason, boot=True)


def attach_engine_service(
    supervisor, coordinator: CheckpointCoordinator, name: str = "engine"
):
    """Register the engine as a supervised, chaos-killable service.

    The engine itself is passive (the router calls into it), so the
    service body is a liveness loop; what makes the kill REAL is the reset
    hook: the supervisor runs ``coordinator.restore()`` before each
    respawn, so a ChaosMonkey kill discards the live engine's
    post-checkpoint state and re-drives it from the bus — the same
    recovery a KIE pod restart goes through.
    """
    stop = threading.Event()
    first = [True]

    def run() -> None:
        stop.wait()

    def reset() -> None:
        stop.clear()
        if first[0]:
            # initial spawn is a boot, not a crash: the live engine already
            # holds the truth and the offsets are wherever the operator put
            # them — restoring here would discard both
            first[0] = False
            return
        coordinator.restore(reason="supervisor-restart")

    supervisor.add_thread_service(name, run, stop.set, reset=reset)
    return supervisor
