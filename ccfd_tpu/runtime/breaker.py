"""Circuit breakers + retry budgets for every RPC edge.

The reference's only failure knob on a service hop is a client-side HTTP
timeout (``SELDON_TIMEOUT``, reference README.md:386-393): a sick endpoint
is re-dialed at full rate and every call eats the full timeout — the ingest
loop stalls at exactly the moment load is highest. This module is the
standard remedy (Hystrix-style breakers, SRE load-shedding literature —
PAPERS.md): per-edge circuit breakers with rolling error+latency windows,
and retry backoff that is exponential with jitter under a deadline budget
instead of linear and unbounded.

States: CLOSED (calls flow; outcomes recorded into a rolling window) →
OPEN when the window's failure ratio crosses the threshold (calls are
refused *instantly* — the edge gets no traffic and the caller falls to its
degraded tier) → HALF_OPEN after a cooldown (a bounded number of probe
calls test the edge) → CLOSED again after consecutive probe successes, or
back to OPEN on a probe failure with the cooldown doubled (+ jitter), so a
flapping edge is re-probed at a gently decaying rate.

Slow calls count as failures when ``latency_threshold_s`` is set: an edge
that technically answers but blows the latency budget is sick for the
caller's purposes (this is what turns a *slow-drip* fault into an open
breaker rather than a slow pipeline).

Breakers export their state per edge (``ccfd_breaker_state``: 0 closed,
1 half-open, 2 open) and transition counters when built with a registry,
which is what the Resilience Grafana board reads.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Any, Callable

# gauge values, chosen so "bigger is sicker" reads on a dashboard
CLOSED, HALF_OPEN, OPEN = 0, 1, 2
_STATE_NAMES = {CLOSED: "closed", HALF_OPEN: "half_open", OPEN: "open"}


class CircuitOpenError(ConnectionError):
    """The breaker refused the call without touching the edge."""


class CircuitBreaker:
    """Thread-safe three-state breaker over a rolling outcome window.

    ``clock`` is injectable (monotonic seconds) so state-transition tests
    don't sleep. One breaker guards ONE edge; callers either use
    :meth:`call` or the ``allow()`` / ``record_success`` /
    ``record_failure`` triple when the call shape doesn't compose.
    """

    def __init__(
        self,
        edge: str = "",
        window_s: float = 10.0,
        min_calls: int = 5,
        failure_ratio: float = 0.5,
        latency_threshold_s: float | None = None,
        cooldown_s: float = 1.0,
        cooldown_max_s: float = 30.0,
        half_open_max: int = 1,
        close_after: int = 2,
        seed: int = 0,
        registry=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.edge = edge
        self.window_s = float(window_s)
        self.min_calls = int(min_calls)
        self.failure_ratio = float(failure_ratio)
        self.latency_threshold_s = latency_threshold_s
        self.cooldown_s = float(cooldown_s)
        self.cooldown_max_s = float(cooldown_max_s)
        self.half_open_max = int(half_open_max)
        self.close_after = int(close_after)
        self._clock = clock
        self._rng = random.Random(seed)
        self._mu = threading.Lock()
        self._state = CLOSED
        self._window: deque[tuple[float, bool]] = deque()  # (ts, ok)
        self._open_until = 0.0
        self._consecutive_opens = 0
        self._probes_inflight = 0
        self._probe_successes = 0
        self.opens = 0  # lifetime open transitions
        self._g_state = None
        self._c_transitions = None
        if registry is not None:
            self._g_state = registry.gauge(
                "ccfd_breaker_state",
                "circuit state per edge: 0 closed, 1 half-open, 2 open",
            )
            self._g_state.set(CLOSED, labels={"edge": edge})
            self._c_transitions = registry.counter(
                "ccfd_breaker_transitions_total",
                "breaker state transitions by edge and target state",
            )

    # -- state machine (all under _mu) ------------------------------------
    def _set_state(self, state: int) -> None:
        if state == self._state:
            return
        self._state = state
        if self._g_state is not None:
            self._g_state.set(state, labels={"edge": self.edge})
        if self._c_transitions is not None:
            self._c_transitions.inc(
                labels={"edge": self.edge, "to": _STATE_NAMES[state]})

    def _evict(self, now: float) -> None:
        w = self._window
        floor = now - self.window_s
        while w and w[0][0] < floor:
            w.popleft()

    def _trip_open(self, now: float) -> None:
        self._consecutive_opens += 1
        self.opens += 1
        # exponential backoff + jitter on re-opens: a flapping edge gets
        # probed at a decaying rate, and jitter decorrelates a fleet of
        # clients re-probing the same sick endpoint in lockstep
        base = min(self.cooldown_s * 2 ** (self._consecutive_opens - 1),
                   self.cooldown_max_s)
        self._open_until = now + base * (1.0 + 0.5 * self._rng.random())
        self._window.clear()
        self._probes_inflight = 0
        self._probe_successes = 0
        self._set_state(OPEN)

    def allow(self) -> bool:
        """May a call proceed right now? OPEN past its cooldown admits up
        to ``half_open_max`` probes (and moves to HALF_OPEN)."""
        now = self._clock()
        with self._mu:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if now < self._open_until:
                    return False
                self._set_state(HALF_OPEN)
                self._probes_inflight = 0
                self._probe_successes = 0
            # HALF_OPEN: bounded probe admission
            if self._probes_inflight < self.half_open_max:
                self._probes_inflight += 1
                return True
            return False

    def record_success(self, latency_s: float = 0.0) -> None:
        slow = (self.latency_threshold_s is not None
                and latency_s > self.latency_threshold_s)
        now = self._clock()
        with self._mu:
            if self._state == HALF_OPEN:
                self._probes_inflight = max(0, self._probes_inflight - 1)
                if slow:
                    self._trip_open(now)
                    return
                self._probe_successes += 1
                if self._probe_successes >= self.close_after:
                    self._consecutive_opens = 0
                    self._window.clear()
                    self._set_state(CLOSED)
                return
            self._record(now, ok=not slow)

    def record_failure(self, latency_s: float = 0.0) -> None:
        now = self._clock()
        with self._mu:
            if self._state == HALF_OPEN:
                # one failed probe is enough: the edge is still sick
                self._trip_open(now)
                return
            if self._state == OPEN:
                return
            self._record(now, ok=False)

    def _record(self, now: float, ok: bool) -> None:
        self._window.append((now, ok))
        self._evict(now)
        n = len(self._window)
        if n < self.min_calls:
            return
        failures = sum(1 for _, k in self._window if not k)
        if failures / n >= self.failure_ratio:
            self._trip_open(now)

    # -- conveniences ------------------------------------------------------
    @property
    def state(self) -> str:
        with self._mu:
            # surface the pending OPEN->HALF_OPEN edge without a call
            if (self._state == OPEN
                    and self._clock() >= self._open_until):
                return _STATE_NAMES[HALF_OPEN]
            return _STATE_NAMES[self._state]

    def force_close(self) -> None:
        """Deliberate external close, for a caller holding STRONGER
        evidence than a half-open probe could gather (the heal ladder's
        warm re-promotion gate: N consecutive canaries + host parity).
        Clears the outcome window, the reopen backoff and any pending
        cooldown — from OPEN, ``record_success`` is a state no-op and the
        residual cooldown would both refuse the healed edge and read as
        fresh quarantine evidence."""
        with self._mu:
            self._window.clear()
            self._consecutive_opens = 0
            self._open_until = 0.0
            self._probes_inflight = 0
            self._probe_successes = 0
            self._set_state(CLOSED)

    def call(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> Any:
        """Gate + time + record around one call. Raises
        :class:`CircuitOpenError` when the breaker refuses."""
        if not self.allow():
            raise CircuitOpenError(
                f"circuit open for edge {self.edge!r}")
        t0 = self._clock()
        try:
            out = fn(*args, **kwargs)
        except Exception:
            self.record_failure(self._clock() - t0)
            raise
        self.record_success(self._clock() - t0)
        return out

    def guard(self, obj: Any, methods: Any = None) -> Any:
        """Proxy an object so the named public methods run through
        :meth:`call` — the in-process analog of wiring the breaker into an
        HTTP client (e.g. the router's ``EngineClient`` edge)."""
        return MethodProxy(obj, self.call,
                           frozenset(methods) if methods else None)


class MethodProxy:
    """Delegating proxy that routes the named public methods through
    ``wrap_call(bound_method, *args, **kwargs)`` — all public callables
    when ``methods`` is None; everything else (attributes, private and
    unlisted methods) passes through untouched, so the proxy keeps the
    wrapped client's full surface. Shared by the breaker's :meth:`guard`
    and the fault injector's ``wrap`` (runtime/faults.py)."""

    def __init__(self, inner: Any, wrap_call: Callable[..., Any],
                 methods: frozenset[str] | None):
        object.__setattr__(self, "_inner", inner)
        object.__setattr__(self, "_wrap_call", wrap_call)
        object.__setattr__(self, "_methods", methods)

    def __getattr__(self, name: str) -> Any:
        attr = getattr(self._inner, name)
        if (not name.startswith("_") and callable(attr)
                and (self._methods is None or name in self._methods)):
            wrap_call = self._wrap_call

            def guarded(*args: Any, **kwargs: Any) -> Any:
                return wrap_call(attr, *args, **kwargs)

            return guarded
        return attr

    def __setattr__(self, name: str, value: Any) -> None:
        setattr(self._inner, name, value)


def backoff_s(
    attempt: int,
    base_s: float = 0.05,
    cap_s: float = 2.0,
    rng: random.Random | None = None,
) -> float:
    """Exponential backoff with *decorrelating* jitter for retry attempt
    ``attempt`` (0-based): uniform in [half, full] of ``base * 2^attempt``,
    capped. The [0.5, 1.0] band keeps a floor (pure full-jitter can draw ~0
    and hammer a recovering server) while still spreading a thundering
    herd. Deterministic when handed a seeded ``rng`` (tests assert the
    bounds)."""
    full = min(base_s * (2 ** attempt), cap_s)
    r = (rng or random).random()
    return full * (0.5 + 0.5 * r)


def call_with_retries(
    fn: Callable[[], Any],
    retries: int,
    base_backoff_s: float = 0.05,
    max_backoff_s: float = 2.0,
    deadline_s: float | None = None,
    retry_on: tuple[type[BaseException], ...] = (ConnectionError, OSError),
    rng: random.Random | None = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
) -> Any:
    """Bounded retries under a total deadline budget.

    ``retries`` is the number of RE-tries (attempts = retries + 1);
    ``deadline_s`` caps the whole loop — a retry whose backoff would land
    past the budget is not taken (the reference's failure story has only a
    per-attempt timeout, so worst-case latency is attempts × timeout with
    no ceiling; the budget gives callers a real bound to size their own
    SLOs against)."""
    deadline = None if deadline_s is None else clock() + deadline_s
    last: BaseException | None = None
    for attempt in range(max(1, retries + 1)):
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 - retry loop by design
            last = e
            if attempt >= retries:
                break
            pause = backoff_s(attempt, base_backoff_s, max_backoff_s, rng)
            if deadline is not None and clock() + pause > deadline:
                break
            sleep(pause)
    assert last is not None
    raise last
