"""Bulk replay & backtest plane (ISSUE 17, ROADMAP item 5).

Re-scores recorded decision history through the SAME serving stack that
made the original calls, under ``bulk`` admission so live traffic keeps
its SLO, and holds the verdict-parity conservation law
``replayed == recorded`` — every divergence is a classified finding,
never a silent diff. See :mod:`ccfd_tpu.replay.service`.
"""

from ccfd_tpu.replay.service import (  # noqa: F401
    CAUSE_CHAMPION_HASH,
    CAUSE_NONDETERMINISM,
    CAUSE_THRESHOLD,
    CAUSE_TIER,
    ReplayService,
    ReplayVerdictTap,
    classify_divergence,
)
