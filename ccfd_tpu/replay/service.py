"""Bulk replay & backtest: re-score recorded history through the live stack.

PR 14 gave every routed transaction a DecisionRecord; this plane is what
USES that provenance at scale (ROADMAP item 5, "Rethinking LLMOps for
Fraud and AML"): regulator audits re-drive a recorded window and prove
the stack still makes the same calls, incident re-drives replay the
transactions that were in flight around a breach, and challenger
backtests ask "what would the new threshold/checkpoint have decided".

The conservation law is ``replayed verdict == recorded verdict`` —
checked per row, byte-stable on the score. Any divergence is itself a
finding, classified by cause:

==================  ======================================================
cause               meaning
==================  ======================================================
``champion_hash``   a different champion checkpoint served the replay
                    (lifecycle moved on — expected after a promote)
``tier``            the serving tier differs (device vs host vs rules:
                    a quarantine/breaker state change, not a model change)
``threshold``       the FRAUD_THRESHOLD in force changed, so the same
                    score routed differently
``nondeterminism``  none of the above explains it — the alarming one
==================  ======================================================

plus window-accounting findings: a ``drop`` (a recorded row whose replay
never produced a verdict after retries) and a ``ghost`` (a replay-marked
verdict for a uid the window never contained).

Mechanics — the SAME path, not a parallel scorer:

- The window source is :meth:`AuditLog.scan_window` over the on-disk
  segments (read-only by contract), or a FlightRecorder bundle's
  embedded decision summaries (:func:`bundle_window` -> seq range ->
  the same segment scan). Windows are re-scorable because the route
  seam embeds the decoded feature row in each record while the replay
  plane is armed (``AuditLog.capture_rows``).
- Re-production goes through the live bus: each recorded row becomes a
  dict transaction (identical feature values, so the decode seam
  rebuilds the identical float32 row) produced onto the transaction
  topic with a ``priority: bulk`` header and a ``_replay`` marker. The
  live router admits it under the PR 6 overload plane — the bulk
  ceiling (:meth:`OverloadControl.set_bulk_ceiling`) caps the share of
  the adaptive budget replay may occupy, which is the zero-live-SLO
  guarantee: live traffic keeps the rest, AIMD keeps both honest.
- At the route seam the replayed decision is stamped like any other,
  but the :class:`ReplayVerdictTap` (the FleetLedgerTap idiom) diverts
  replay-marked rows to the join instead of the audit plane — replays
  never pollute the provenance log they are checked against.
- Progress is a crash-resumable cursor written through the PR 13
  durability seam after each joined batch: kill the worker mid-window,
  restart, and the window completes with exactly-once accounting (the
  bus re-production is at-least-once; the JOIN ledger is exactly-once —
  a late duplicate verdict counts as ``dup`` and changes nothing). A
  torn cursor falls back a generation (``read_json_artifact``) and the
  batch it loses is simply re-joined.
- What-if mode skips the bus entirely: a caller-supplied score function
  (the challenger checkpoint) and/or a threshold override are diffed
  against the recorded decisions host-side — backtests never touch the
  live serving path.

Metrics: ``ccfd_replay_rows_total{outcome}``,
``ccfd_replay_divergence_total{cause}``,
``ccfd_replay_windows_total{result}``, ``ccfd_replay_cursor_seq``,
``ccfd_replay_rows_per_s``, ``ccfd_bulk_ceiling{stage}`` (overload
plane), plus the tap's ``ccfd_replay_verdicts_total{fate}``.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable, Mapping

import numpy as np

from ccfd_tpu.data.ccfd import FEATURE_NAMES
from ccfd_tpu.runtime import durability

log = logging.getLogger(__name__)

CAUSE_CHAMPION_HASH = "champion_hash"
CAUSE_TIER = "tier"
CAUSE_THRESHOLD = "threshold"
CAUSE_NONDETERMINISM = "nondeterminism"

# bounded findings ledger per window: enough to triage, never unbounded
MAX_FINDINGS = 256


def classify_divergence(recorded: Mapping[str, Any],
                        replayed: Mapping[str, Any]) -> str | None:
    """None when parity holds (score, rule and branch byte-equal under
    the same threshold); otherwise the FIRST cause in precedence order
    that explains the divergence. Precedence matters: a champion swap
    usually changes the score too — blaming ``nondeterminism`` for a
    known promote would cry wolf on the only cause that is a bug."""
    same = (
        float(recorded.get("proba", -1.0)) == float(
            replayed.get("proba", -2.0))
        and recorded.get("rule") == replayed.get("rule")
        and recorded.get("branch") == replayed.get("branch")
        and _thr(recorded) == _thr(replayed)
    )
    if same:
        return None
    rec_h, rep_h = recorded.get("hash"), replayed.get("hash")
    if rec_h is not None and rep_h is not None and rec_h != rep_h:
        return CAUSE_CHAMPION_HASH
    if recorded.get("tier", "device") != replayed.get("tier", "device"):
        return CAUSE_TIER
    if _thr(recorded) != _thr(replayed):
        return CAUSE_THRESHOLD
    return CAUSE_NONDETERMINISM


def _thr(rec: Mapping[str, Any]) -> float | None:
    t = rec.get("threshold")
    return None if t is None else float(t)


def bundle_window(bundle: Mapping[str, Any]) -> tuple[int, int] | None:
    """FlightRecorder incident bundle -> the (since_seq, until_seq) of
    the decisions in flight across the breach window (the v2
    ``decisions`` embed), or None when the bundle has no decisions.
    The full records come from the segment scan — the bundle only
    brackets the window."""
    seqs = []
    for d in bundle.get("decisions") or ():
        try:
            seqs.append(int(d["seq"]))
        except (KeyError, TypeError, ValueError):
            continue
    if not seqs:
        return None
    return min(seqs), max(seqs)


class ReplayVerdictTap:
    """Audit-shaped route-seam tap that diverts replay-marked decisions.

    Sits where the router expects its audit sink (duck-typed
    ``record_batch``, the FleetLedgerTap idiom): live rows forward to
    the real :class:`AuditLog` untouched; rows stamped with a ``replay``
    marker go to the armed join sink instead — replayed verdicts must
    never land in the provenance log they are being checked against
    (they would re-stamp the original uids' transactions and poison the
    very window a re-drive reads). Never raises into the route seam."""

    def __init__(self, inner=None, registry=None):
        self.inner = inner
        self._sink: Callable[..., None] | None = None
        self._c_verdicts = None
        if registry is not None:
            self._c_verdicts = registry.counter(
                "ccfd_replay_verdicts_total",
                "replay-marked decisions leaving the route seam by fate: "
                "joined = handed to the armed window join; orphaned = no "
                "window armed (a replay worker died mid-window — the "
                "verdicts are dropped here and the resumed worker "
                "re-produces them)",
            )

    @property
    def capture_rows(self) -> bool:
        # the route seam asks the audit sink whether to embed feature
        # rows; the tap answers for the wrapped log
        return bool(self.inner is not None
                    and getattr(self.inner, "capture_rows", False))

    def arm(self, sink: Callable[..., None]) -> None:
        self._sink = sink

    def disarm(self) -> None:
        self._sink = None

    def record_batch(self, rows: list, *, tier: str = "device",
                     cause: str | None = None, events: tuple | list = (),
                     worker: int | None = None, trace_id: str | None = None,
                     threshold: float | None = None) -> None:
        live = [r for r in rows if r.get("replay") is None]
        replayed = [r for r in rows if r.get("replay") is not None]
        if live and self.inner is not None:
            self.inner.record_batch(
                live, tier=tier, cause=cause, events=events, worker=worker,
                trace_id=trace_id, threshold=threshold)
        if not replayed:
            return
        sink = self._sink
        fate = "orphaned" if sink is None else "joined"
        if self._c_verdicts is not None:
            self._c_verdicts.inc(len(replayed), labels={"fate": fate})
        if sink is None:
            return
        try:
            sink(replayed, tier=tier, cause=cause, threshold=threshold)
        except Exception:  # noqa: BLE001 - the join must not crash routing
            log.exception("replay verdict sink failed (%d verdicts)",
                          len(replayed))


class ReplayKilled(BaseException):
    """Raised by test crash hooks to simulate a worker dying mid-window.
    BaseException so production ``except Exception`` seams never swallow
    the simulated kill."""


class ReplayService:
    """Windowed replay with verdict-parity accounting; module docstring
    has the plane's contract. One instance per platform; thread-safe
    between the run loop and the tap's verdict callbacks."""

    def __init__(
        self,
        cfg,
        broker,
        audit,
        tap: ReplayVerdictTap | None = None,
        registry=None,
        state_dir: str | None = None,
        overload=None,
        gate=None,
        lineage_fn: Callable[[], tuple[Any, Any]] | None = None,
        clock: Callable[[], float] = time.time,
    ):
        self.cfg = cfg
        self.broker = broker
        self.audit = audit
        self.tap = tap
        self.overload = overload
        self.gate = gate
        self.lineage_fn = lineage_fn
        self._clock = clock
        self.state_dir = state_dir or None
        self.batch = max(1, int(getattr(cfg, "replay_batch", 256)))
        self.timeout_s = float(getattr(cfg, "replay_timeout_s", 10.0))
        self.retries = max(0, int(getattr(cfg, "replay_retries", 3)))
        self.bulk_ceiling = float(getattr(cfg, "replay_bulk_ceiling", 0.5))
        # operator-settable pacing knob (rows/second; 0 = saturate the
        # bulk share) — the future capacity planner's actuator
        self.pacing_rows_s = float(getattr(cfg, "replay_pacing_rows_s", 0.0))
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._inbox: dict[str, dict[str, dict]] = {}
        self._window_uids: dict[str, set[str]] = {}
        self._joined: dict[str, set[str]] = {}
        self._dups = 0
        self._ghosts: dict[str, list[str]] = {}
        self._stop = threading.Event()
        self._requests: list[dict] = []
        self.last_report: dict | None = None
        # test seam: called at ("produced"|"joined"|"committed", batch_i);
        # a hook that raises simulates a kill at exactly that boundary
        self.crash_hook: Callable[[str, int], None] | None = None
        self._c_rows = self._c_div = self._c_windows = None
        self._g_cursor = self._g_rate = None
        if registry is not None:
            self._c_rows = registry.counter(
                "ccfd_replay_rows_total",
                "replayed window rows by outcome: match (parity held), "
                "divergence, drop (no verdict after retries), ghost "
                "(verdict for a uid outside the window), dup (late "
                "duplicate verdict, ignored by the exactly-once join), "
                "no_row (record predates feature capture — not "
                "re-scorable)",
            )
            self._c_div = registry.counter(
                "ccfd_replay_divergence_total",
                "parity divergences by classified cause (champion_hash / "
                "tier / threshold / nondeterminism) — nondeterminism "
                "must stay 0; anything else is an explained finding",
            )
            self._c_windows = registry.counter(
                "ccfd_replay_windows_total",
                "completed replay windows by result (clean = every row "
                "matched; findings = at least one divergence/drop/ghost)",
            )
            self._g_cursor = registry.gauge(
                "ccfd_replay_cursor_seq",
                "highest recorded seq the durable replay cursor covers",
            )
            self._g_rate = registry.gauge(
                "ccfd_replay_rows_per_s",
                "replay re-score throughput over the last window",
            )
        if self.state_dir:
            os.makedirs(self.state_dir, exist_ok=True)
        if self.tap is not None:
            self.tap.arm(self._on_verdicts)
        if self.audit is not None:
            # arm feature capture so windows recorded from now on are
            # self-contained and re-scorable off the segments alone
            self.audit.capture_rows = True

    # -- the verdict join (tap callback; router worker threads) -----------
    def _on_verdicts(self, rows: list, *, tier: str = "device",
                     cause: str | None = None,
                     threshold: float | None = None) -> None:
        ver = hsh = None
        if self.lineage_fn is not None:
            try:
                ver, hsh = self.lineage_fn()
            except Exception:  # noqa: BLE001 - classification survives a
                pass           # failed lineage probe (hash stays None)
        with self._cv:
            for r in rows:
                mk = r.get("replay") or {}
                wid, uid = str(mk.get("w")), str(mk.get("uid"))
                uids = self._window_uids.get(wid)
                if uids is None or uid not in uids:
                    self._ghosts.setdefault(wid, []).append(uid)
                    continue
                if uid in self._joined.setdefault(wid, set()):
                    self._dups += 1
                    continue
                self._inbox.setdefault(wid, {})[uid] = {
                    "proba": r.get("proba"),
                    "rule": r.get("rule"),
                    "branch": r.get("branch"),
                    "pid": r.get("pid"),
                    "uid": r.get("uid"),
                    "tier": tier,
                    "cause": cause,
                    "threshold": threshold,
                    "version": ver,
                    "hash": hsh,
                }
            self._cv.notify_all()

    # -- pacing / admission knobs -----------------------------------------
    def set_pacing(self, rows_per_s: float) -> None:
        self.pacing_rows_s = max(0.0, float(rows_per_s))

    def set_bulk_ceiling(self, frac: float) -> None:
        self.bulk_ceiling = min(1.0, max(0.0, float(frac)))
        for target in (self.overload, self.gate):
            if target is not None:
                target.set_bulk_ceiling(self.bulk_ceiling)

    # -- cursor (PR 13 durability seam) ------------------------------------
    def _cursor_path(self, wid: str) -> str:
        safe = "".join(c if (c.isalnum() or c in "-_.") else "_"
                       for c in wid)
        return os.path.join(self.state_dir, f"replay-cursor-{safe}.json")

    def _load_cursor(self, wid: str, total: int) -> dict | None:
        if not self.state_dir:
            return None
        try:
            cur = durability.read_json_artifact(
                self._cursor_path(wid), artifact="replay_cursor")
        except FileNotFoundError:
            return None
        except (ValueError, durability.CorruptArtifactError):
            # main AND every retained generation failed to verify (or an
            # unframed legacy file held non-JSON bytes): the window
            # restarts from zero — re-joining is idempotent
            log.warning("replay cursor for window %s unrecoverable; "
                        "restarting the window", wid)
            return None
        if (not isinstance(cur, dict) or cur.get("window_id") != wid
                or int(cur.get("total", -1)) != total):
            return None  # a different window under the same id: restart
        return cur

    def _commit_cursor(self, wid: str, doc: dict) -> None:
        if self.state_dir:
            durability.write_json_artifact(
                self._cursor_path(wid), doc, artifact="replay_cursor")
        if self._g_cursor is not None and doc.get("last_seq") is not None:
            self._g_cursor.set(float(doc["last_seq"]))

    # -- the window drive ---------------------------------------------------
    def run_window(
        self,
        since_seq: int | None = None,
        until_seq: int | None = None,
        *,
        window: list[Mapping[str, Any]] | None = None,
        window_id: str | None = None,
        mode: str = "replay",
        threshold: float | None = None,
        score_fn: Callable[[np.ndarray], np.ndarray] | None = None,
        resume: bool = True,
    ) -> dict:
        """Replay one recorded window; returns the parity report.

        ``window`` overrides the segment scan (an explicit record list —
        the FlightRecorder path hands the ``bundle_window`` seq range to
        the scan instead). ``mode="whatif"`` diffs host-side under a
        ``threshold`` override and/or challenger ``score_fn`` without
        touching the bus. Kill-and-restart safe when ``resume`` (the
        default): the durable cursor skips completed batches."""
        recs = (list(window) if window is not None
                else self.audit.scan_window(since_seq, until_seq))
        recs.sort(key=lambda r: int(r.get("seq", -1)))
        rows = [r for r in recs if r.get("row") is not None]
        no_row = len(recs) - len(rows)
        if no_row:
            self._count_rows("no_row", no_row)
        wid = window_id or (
            f"{recs[0].get('seq', 0)}-{recs[-1].get('seq', 0)}"
            if recs else "empty")
        if mode == "whatif":
            return self._run_whatif(wid, rows, no_row, threshold, score_fn)
        return self._run_replay(wid, rows, no_row, resume)

    def _run_replay(self, wid: str, rows: list, no_row: int,
                    resume: bool) -> dict:
        t0 = self._clock()
        start = 0
        counts = {"match": 0, "divergence": 0, "drop": 0}
        causes: dict[str, int] = {}
        findings: list[dict] = []
        cur = self._load_cursor(wid, len(rows)) if resume else None
        if cur is not None:
            start = int(cur.get("next", 0))
            counts = dict(cur.get("counts", counts))
            causes = dict(cur.get("causes", {}))
            findings = list(cur.get("findings", []))
            log.info("replay window %s resuming at row %d/%d",
                     wid, start, len(rows))
        with self._cv:
            self._window_uids[wid] = {str(r.get("uid")) for r in rows}
            self._inbox.setdefault(wid, {})
            # the joined set rebuilds from the cursor: completed batches
            # must not re-join even if the live stack re-scores them
            self._joined[wid] = {str(r.get("uid")) for r in rows[:start]}
        prev_ceilings = []
        for target in (self.overload, self.gate):
            if target is not None:
                prev_ceilings.append((target, target.bulk_ceiling))
                target.set_bulk_ceiling(self.bulk_ceiling)
        stopped = False
        try:
            i = start
            while i < len(rows) and not self._stop.is_set():
                batch = rows[i:i + self.batch]
                bi = i // self.batch
                joined = self._drive_batch(wid, batch, bi)
                if self.crash_hook is not None:
                    self.crash_hook("joined", bi)
                for rec in batch:
                    uid = str(rec.get("uid"))
                    rep = joined.get(uid)
                    if rep is None:
                        counts["drop"] += 1
                        self._count_rows("drop", 1)
                        self._finding(findings, "drop", rec, None, None)
                        continue
                    cause = classify_divergence(rec, rep)
                    if cause is None:
                        counts["match"] += 1
                        self._count_rows("match", 1)
                    else:
                        counts["divergence"] += 1
                        causes[cause] = causes.get(cause, 0) + 1
                        self._count_rows("divergence", 1)
                        if self._c_div is not None:
                            self._c_div.inc(labels={"cause": cause})
                        self._finding(findings, "divergence", rec, rep,
                                      cause)
                i += len(batch)
                self._commit_cursor(wid, {
                    "window_id": wid, "total": len(rows), "next": i,
                    "counts": counts, "causes": causes,
                    "findings": findings[:MAX_FINDINGS],
                    "last_seq": (int(batch[-1].get("seq", -1))
                                 if batch else None),
                })
                if self.crash_hook is not None:
                    self.crash_hook("committed", bi)
                self._pace(len(batch), t0, i - start)
            stopped = i < len(rows)
        finally:
            for target, prev in prev_ceilings:
                target.set_bulk_ceiling(prev)
        with self._cv:
            ghosts = self._ghosts.pop(wid, [])
            self._window_uids.pop(wid, None)
            self._inbox.pop(wid, None)
            self._joined.pop(wid, None)
        for g in ghosts:
            self._count_rows("ghost", 1)
            self._finding(findings, "ghost", {"uid": g}, None, None)
        elapsed = max(1e-9, self._clock() - t0)
        replayed = counts["match"] + counts["divergence"]
        report = {
            "window_id": wid, "mode": "replay", "total": len(rows),
            "no_row": no_row, "resumed_at": start, "stopped": stopped,
            "replayed": replayed, "match": counts["match"],
            "divergence": counts["divergence"], "drop": counts["drop"],
            "ghost": len(ghosts), "dup": self._dups, "causes": causes,
            "parity": (counts["divergence"] == 0 and counts["drop"] == 0
                       and not ghosts and not stopped),
            "elapsed_s": elapsed,
            "rows_per_s": (replayed + counts["drop"]) / elapsed,
            "findings": findings[:MAX_FINDINGS],
        }
        if self._g_rate is not None:
            self._g_rate.set(report["rows_per_s"])
        if self._c_windows is not None and not stopped:
            self._c_windows.inc(labels={
                "result": "clean" if report["parity"] else "findings"})
        self.last_report = report
        return report

    def _drive_batch(self, wid: str, batch: list, bi: int) -> dict:
        """Produce one batch through the live bus at bulk priority and
        collect its verdicts. Re-production is at-least-once (bulk rows
        may legitimately shed under live load — that IS the SLO
        guarantee working), so unanswered rows retry up to
        ``retries``; the join stays exactly-once via the joined set."""
        pending = {str(r.get("uid")): r for r in batch}
        joined: dict[str, dict] = {}
        for attempt in range(self.retries + 1):
            if not pending or self._stop.is_set():
                break
            self._produce(wid, list(pending.values()))
            if self.crash_hook is not None and attempt == 0:
                self.crash_hook("produced", bi)
            deadline = time.monotonic() + self.timeout_s
            with self._cv:
                while pending:
                    box = self._inbox.get(wid, {})
                    for uid in list(pending):
                        rep = box.pop(uid, None)
                        if rep is not None:
                            joined[uid] = rep
                            self._joined.setdefault(wid, set()).add(uid)
                            del pending[uid]
                    if not pending:
                        break
                    left = deadline - time.monotonic()
                    if left <= 0 or self._stop.is_set():
                        break
                    self._cv.wait(min(left, 0.25))
            if pending and attempt < self.retries:
                log.info("replay window %s batch %d: %d rows unanswered, "
                         "re-producing (attempt %d)", wid, bi,
                         len(pending), attempt + 2)
        return joined

    def _produce(self, wid: str, batch: list) -> None:
        values = []
        keys = []
        for rec in batch:
            tx = dict(zip(FEATURE_NAMES, (float(v) for v in rec["row"])))
            tx["id"] = rec.get("tx")
            tx["_replay"] = {"w": wid, "uid": str(rec.get("uid"))}
            values.append(tx)
            keys.append(rec.get("tx"))
        self.broker.produce_batch(
            self.cfg.kafka_topic, values, keys=keys,
            headers={"priority": "bulk"})

    def _pace(self, batch_rows: int, t0: float, done_rows: int) -> None:
        if self.pacing_rows_s <= 0 or batch_rows <= 0:
            return
        # absolute schedule (rows done vs elapsed), so a slow batch
        # earns back its debt instead of compounding the delay
        ahead_s = done_rows / self.pacing_rows_s - (self._clock() - t0)
        if ahead_s > 0:
            self._stop.wait(min(ahead_s, 5.0))

    # -- what-if (backtest; never touches the live path) -------------------
    def _run_whatif(self, wid: str, rows: list, no_row: int,
                    threshold: float | None,
                    score_fn: Callable[[np.ndarray], np.ndarray] | None
                    ) -> dict:
        t0 = self._clock()
        flips = []
        n_flips = 0
        deltas = []
        for i in range(0, len(rows), self.batch):
            batch = rows[i:i + self.batch]
            x = np.asarray([r["row"] for r in batch], np.float32)
            if score_fn is not None:
                proba = np.asarray(score_fn(x), np.float64).reshape(-1)
            else:
                proba = np.asarray([float(r.get("proba", 0.0))
                                    for r in batch], np.float64)
            for rec, p in zip(batch, proba.tolist()):
                thr_rec = _thr(rec)
                thr_new = threshold if threshold is not None else thr_rec
                was = (thr_rec is not None
                       and float(rec.get("proba", 0.0)) >= thr_rec)
                now = thr_new is not None and p >= thr_new
                deltas.append(abs(p - float(rec.get("proba", 0.0))))
                if was != now:
                    n_flips += 1
                    if len(flips) < MAX_FINDINGS:
                        flips.append({
                            "uid": rec.get("uid"), "tx": rec.get("tx"),
                            "recorded": {"proba": rec.get("proba"),
                                         "threshold": thr_rec,
                                         "fraud": was},
                            "whatif": {"proba": p, "threshold": thr_new,
                                       "fraud": now},
                        })
        elapsed = max(1e-9, self._clock() - t0)
        report = {
            "window_id": wid, "mode": "whatif", "total": len(rows),
            "no_row": no_row, "threshold": threshold,
            "challenger": score_fn is not None, "flips": n_flips,
            "flip_rate": (n_flips / len(rows)) if rows else 0.0,
            "mean_abs_delta": (sum(deltas) / len(deltas)) if deltas
            else 0.0,
            "elapsed_s": elapsed, "rows_per_s": len(rows) / elapsed,
            "findings": flips,
        }
        self.last_report = report
        return report

    # -- findings / accounting ---------------------------------------------
    def _finding(self, findings: list, kind: str, rec, rep,
                 cause: str | None) -> None:
        if len(findings) >= MAX_FINDINGS:
            return
        f: dict[str, Any] = {"kind": kind, "uid": rec.get("uid"),
                             "tx": rec.get("tx"), "seq": rec.get("seq")}
        if cause is not None:
            f["cause"] = cause
        if rep is not None:
            f["recorded"] = {k: rec.get(k) for k in
                             ("proba", "rule", "branch", "tier",
                              "threshold", "hash") if rec.get(k) is not None}
            f["replayed"] = {k: rep.get(k) for k in
                             ("proba", "rule", "branch", "tier",
                              "threshold", "hash") if rep.get(k) is not None}
        findings.append(f)

    def _count_rows(self, outcome: str, n: int) -> None:
        if self._c_rows is not None and n > 0:
            self._c_rows.inc(n, labels={"outcome": outcome})

    # -- supervised-service surface ----------------------------------------
    def submit(self, **request) -> None:
        """Queue a window for the supervised run loop (the operator's
        component thread)."""
        with self._cv:
            self._requests.append(request)
            self._cv.notify_all()

    def reset(self) -> None:
        self._stop.clear()

    def stop(self) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()

    def run(self, interval_s: float = 0.25) -> None:
        while not self._stop.is_set():
            with self._cv:
                req = self._requests.pop(0) if self._requests else None
                if req is None:
                    self._cv.wait(interval_s)
                    continue
            try:
                self.run_window(**req)
            except Exception:  # noqa: BLE001 - one bad window must not
                log.exception("replay window failed")  # kill the plane
