"""TPU-native batch analytics: the Spark / notebook-cluster analog.

The reference platform CR provisions JupyterHub notebooks backed by a
2-worker Spark cluster (3 cpu / 4Gi each) for exploratory dataset analytics
and offline model work (reference deploy/frauddetection_cr.yaml:7-42,
spark-operator 44-53), observable on a dedicated executor-metrics Grafana
board (reference deploy/grafana/SparkMetrics.json). This module re-designs
that capability TPU-first: instead of a JVM executor cluster shuffling rows,
a dataset summary is a pair of jitted XLA programs — moments, extrema, class
aggregates and the feature Gram matrix fuse into one pass over rows sharded
across the device mesh's data axis (XLA's psum over ICI replaces Spark's
shuffle), and per-feature histograms run a second fused pass once the
extrema fix the bin edges. The Gram matrix rides the MXU; everything else is
HBM-bandwidth-bound and fuses into the surrounding reduction.

Built-in "jobs" (what the reference notebooks do by hand):

- ``AnalyticsEngine.summarize`` — per-feature mean/std/min/max + histograms,
  class balance, per-class amount aggregates, feature correlation matrix.
- ``AnalyticsEngine.drift`` — population-stability-index per feature between
  a reference :class:`Report` (the training distribution) and a serving
  window — the drift question the ModelPrediction board exists to answer
  (reference deploy/grafana/ModelPrediction.json:96-322 plots raw feature
  streams for exactly this).
- :class:`DriftMonitor` — a supervised service consuming the live
  transaction topic (the analytics consumer group sits beside the router's,
  reference deploy/router.yaml:61-62) and exporting PSI gauges.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ccfd_tpu.config import Config
from ccfd_tpu.data.ccfd import FEATURE_NAMES, NUM_FEATURES
from ccfd_tpu.runtime.durability import CorruptArtifactError
from ccfd_tpu.parallel.mesh import DATA_AXIS, make_mesh

DEFAULT_NBINS = 32
_EPS = 1e-6


class Report(NamedTuple):
    """Replicated output of one summarize job (all arrays host numpy)."""

    n: int
    mean: np.ndarray          # (F,)
    std: np.ndarray           # (F,)
    min: np.ndarray           # (F,)
    max: np.ndarray           # (F,)
    hist: np.ndarray          # (F, nbins) counts
    edges: np.ndarray         # (F, nbins + 1) shared-binning edges
    corr: np.ndarray          # (F, F) Pearson correlation
    class_counts: np.ndarray  # (2,) rows per Class label
    amount_sum_by_class: np.ndarray  # (2,)

    def save(self, path: str) -> str:
        """Persist the report (one .npz, tmp+rename crash-safe) so a PSI
        baseline survives restarts — the DriftMonitor otherwise loses its
        reference distribution on every bring-up and must re-summarize the
        training set before the first drift score."""
        import io

        from ccfd_tpu.runtime.durability import write_artifact

        buf = io.BytesIO()
        np.savez(
            buf,
            n=np.int64(self.n),
            **{k: np.asarray(getattr(self, k))
               for k in ("mean", "std", "min", "max", "hist", "edges",
                         "corr", "class_counts", "amount_sum_by_class")},
        )
        write_artifact(path, buf.getvalue(), artifact="drift_reference")
        return path

    @staticmethod
    def load(path: str) -> "Report":
        """Verified read (runtime/durability.py): a corrupt reference
        quarantines and the last-good retained generation loads — the PSI
        baseline degrades to slightly stale, never to garbage."""
        import io

        from ccfd_tpu.runtime.durability import read_artifact

        data = np.load(io.BytesIO(read_artifact(
            path, artifact="drift_reference")))
        return Report(
            n=int(data["n"]),
            mean=data["mean"], std=data["std"],
            min=data["min"], max=data["max"],
            hist=data["hist"], edges=data["edges"], corr=data["corr"],
            class_counts=data["class_counts"],
            amount_sum_by_class=data["amount_sum_by_class"],
        )

    def to_dict(self) -> dict[str, Any]:
        n1 = float(max(self.class_counts[1], 0.0))
        return {
            "rows": self.n,
            "fraud_rate": n1 / max(self.n, 1),
            "class_counts": self.class_counts.tolist(),
            "amount_mean_by_class": [
                float(s / max(c, 1.0))
                for s, c in zip(self.amount_sum_by_class, self.class_counts)
            ],
            "features": {
                name: {
                    "mean": float(self.mean[i]),
                    "std": float(self.std[i]),
                    "min": float(self.min[i]),
                    "max": float(self.max[i]),
                }
                for i, name in enumerate(FEATURE_NAMES)
            },
        }


def _moments_job(x: jnp.ndarray, y: jnp.ndarray, mask: jnp.ndarray):
    """One fused pass: moments + extrema + Gram + class aggregates.

    ``x`` is (N, F) sharded on rows; every output is a full reduction over
    the sharded axis, so under ``jit`` XLA lowers the cross-shard combine to
    psums over ICI — the collective layout Spark's shuffle becomes on TPU.
    """
    m = mask[:, None].astype(jnp.float32)
    xm = x * m
    n = jnp.sum(mask.astype(jnp.float32))
    s = jnp.sum(xm, axis=0)
    sq = jnp.sum(xm * x, axis=0)
    lo = jnp.min(jnp.where(m > 0, x, jnp.inf), axis=0)
    hi = jnp.max(jnp.where(m > 0, x, -jnp.inf), axis=0)
    # Gram matrix on the MXU; f32 accumulation keeps corr numerically sane.
    gram = jnp.einsum(
        "nf,ng->fg", xm, x, precision=jax.lax.Precision.HIGHEST
    )
    y1 = (y > 0).astype(jnp.float32) * mask.astype(jnp.float32)
    y0 = mask.astype(jnp.float32) - y1
    amount = x[:, NUM_FEATURES - 1]
    return {
        "n": n,
        "sum": s,
        "sumsq": sq,
        "min": lo,
        "max": hi,
        "gram": gram,
        "class_counts": jnp.stack([jnp.sum(y0), jnp.sum(y1)]),
        "amount_sum_by_class": jnp.stack(
            [jnp.sum(y0 * amount), jnp.sum(y1 * amount)]
        ),
    }


def _hist_job(x: jnp.ndarray, mask: jnp.ndarray, lo: jnp.ndarray, hi: jnp.ndarray, nbins: int):
    """Second fused pass: per-feature counts against [lo, hi) linear bins."""
    width = jnp.maximum(hi - lo, _EPS)
    idx = jnp.clip(
        jnp.floor((x - lo[None, :]) / width[None, :] * nbins).astype(jnp.int32),
        0,
        nbins - 1,
    )
    onehot = (idx[:, :, None] == jnp.arange(nbins)[None, None, :])
    return jnp.sum(
        onehot * mask[:, None, None].astype(jnp.float32), axis=0
    )


def psi(p_hist: np.ndarray, q_hist: np.ndarray) -> np.ndarray:
    """Population stability index per feature between two (F, B) histograms.

    Standard fraud-ops drift score: PSI < 0.1 stable, 0.1–0.25 drifting,
    > 0.25 action needed. Counts are eps-smoothed so empty bins don't blow
    up the log ratio.
    """
    p = np.asarray(p_hist, np.float64) + _EPS
    q = np.asarray(q_hist, np.float64) + _EPS
    p /= p.sum(axis=-1, keepdims=True)
    q /= q.sum(axis=-1, keepdims=True)
    return np.sum((p - q) * np.log(p / q), axis=-1)


class AnalyticsEngine:
    """Mesh-sharded batch analytics over CCFD feature matrices."""

    def __init__(self, mesh=None, nbins: int = DEFAULT_NBINS, registry=None):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.nbins = int(nbins)
        self._rows = NamedSharding(self.mesh, P(DATA_AXIS, None))
        self._vec = NamedSharding(self.mesh, P(DATA_AXIS))
        rep = NamedSharding(self.mesh, P())
        self._moments = jax.jit(
            _moments_job,
            in_shardings=(self._rows, self._vec, self._vec),
            out_shardings=rep,
        )
        self._hist = jax.jit(
            _hist_job,
            static_argnames=("nbins",),
            in_shardings=(self._rows, self._vec, rep, rep),
            out_shardings=rep,
        )
        self._c_jobs = self._h_job_s = self._c_rows = None
        if registry is not None:
            self._c_jobs = registry.counter(
                "analytics_jobs_completed_total", "batch analytics jobs run"
            )
            self._h_job_s = registry.histogram(
                "analytics_job_seconds", "analytics job wall time"
            )
            self._c_rows = registry.counter(
                "analytics_rows_processed_total", "rows aggregated"
            )
            registry.gauge(
                "analytics_workers", "devices in the analytics mesh"
            ).set(self.mesh.size)

    # -- sharding helpers --------------------------------------------------
    def _pad(self, x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        n = x.shape[0]
        shards = self.mesh.shape[DATA_AXIS]
        pad = (-n) % shards
        if pad:
            x = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)])
        mask = np.zeros(n + pad, np.float32)
        mask[:n] = 1.0
        return x, mask

    def _account(self, job: str, n_rows: int, t0: float) -> None:
        if self._c_jobs is not None:
            self._c_jobs.inc(labels={"job": job})
            self._h_job_s.observe(time.perf_counter() - t0)
            self._c_rows.inc(n_rows)

    # -- jobs --------------------------------------------------------------
    def summarize(self, x: np.ndarray, y: np.ndarray | None = None) -> Report:
        t0 = time.perf_counter()
        x = np.asarray(x, np.float32)
        n = x.shape[0]
        if y is None:
            y = np.zeros(n, np.int32)
        xp, mask = self._pad(x)
        yp, _ = self._pad(np.asarray(y, np.int32))
        mom = jax.device_get(self._moments(xp, yp, mask))
        mean = mom["sum"] / max(float(mom["n"]), 1.0)
        var = np.maximum(mom["sumsq"] / max(float(mom["n"]), 1.0) - mean**2, 0.0)
        std = np.sqrt(var)
        lo, hi = mom["min"], mom["max"]
        hist = np.asarray(
            jax.device_get(self._hist(xp, mask, lo, hi, self.nbins))
        )
        edges = lo[:, None] + (hi - lo)[:, None] * np.linspace(
            0.0, 1.0, self.nbins + 1
        )[None, :].astype(np.float32)
        cov = mom["gram"] / max(float(mom["n"]), 1.0) - np.outer(mean, mean)
        denom = np.outer(std, std)
        corr = cov / np.maximum(denom, _EPS)
        np.fill_diagonal(corr, 1.0)
        self._account("summarize", n, t0)
        return Report(
            n=int(mom["n"]),
            mean=mean,
            std=std,
            min=lo,
            max=hi,
            hist=hist,
            edges=edges.astype(np.float32),
            corr=corr,
            class_counts=mom["class_counts"],
            amount_sum_by_class=mom["amount_sum_by_class"],
        )

    def window_hist(self, reference: Report, x: np.ndarray) -> np.ndarray:
        """Histogram a serving window on the reference's bin edges."""
        xp, mask = self._pad(np.asarray(x, np.float32))
        return np.asarray(
            jax.device_get(
                self._hist(xp, mask, reference.min, reference.max, self.nbins)
            )
        )

    def drift(self, reference: Report, x: np.ndarray) -> np.ndarray:
        """Per-feature PSI of a serving window vs the reference distribution."""
        t0 = time.perf_counter()
        scores = psi(self.window_hist(reference, x), reference.hist)
        self._account("drift", int(np.asarray(x).shape[0]), t0)
        return scores


class DriftMonitor:
    """Supervised service: live-topic windows scored for drift vs training.

    Subscribes to the transaction topic in its own consumer group (beside
    the router's, reference deploy/router.yaml:61-62), accumulates a window
    of decoded feature rows, and on each full window exports per-feature PSI
    gauges — the online half of the notebook workflow the reference leaves
    to a human staring at the ModelPrediction board.
    """

    def __init__(
        self,
        cfg: Config,
        broker,
        reference: Report | None,
        engine: AnalyticsEngine | None = None,
        registry=None,
        window: int = 4096,
        reference_builder: Callable[[], Report] | None = None,
        reference_path: str | None = None,
    ):
        self.cfg = cfg
        self.engine = engine if engine is not None else AnalyticsEngine(registry=registry)
        self.reference = reference
        # persisted baseline: a restart reloads the reference histogram
        # instead of rebuilding it from scratch (and a freshly built one
        # is saved back). A stale file with a different binning is
        # ignored — the builder recreates and overwrites it.
        self.reference_path = reference_path
        if reference is None and reference_path:
            import os

            if os.path.exists(reference_path):
                import zipfile

                try:
                    loaded = Report.load(reference_path)
                    if loaded.hist.shape[1] == self.engine.nbins:
                        self.reference = loaded
                # np.load surfaces corruption as BadZipFile (truncated
                # archive) or EOFError (empty file), neither an OSError —
                # and the durability layer raises CorruptArtifactError
                # when NO retained generation verifies. All of them mean
                # "rebuild", never "refuse to start"
                except (OSError, KeyError, ValueError, EOFError,
                        zipfile.BadZipFile, CorruptArtifactError) as e:
                    import logging

                    logging.getLogger(__name__).warning(
                        "drift reference %s unreadable (%r); rebuilding",
                        reference_path, e)
        if self.reference is None and reference_builder is None:
            raise ValueError("need a reference Report, a readable "
                             "reference_path, or a reference_builder")
        # deferred: dataset load + summarize compile can take tens of
        # seconds; built on the supervised thread, not platform bring-up
        self._reference_builder = reference_builder
        self.window = int(window)
        self._broker = broker
        self._group = "ccfd-analytics"
        self._topic = cfg.kafka_topic
        self._consumer = broker.consumer(self._group, (self._topic,))
        self._consumer_closed = False
        self._buf: list[np.ndarray] = []
        self._buffered = 0
        self._stop = threading.Event()
        self.windows_scored = 0
        self._g_psi = self._g_max = None
        if registry is not None:
            self._g_psi = registry.gauge(
                "analytics_drift_psi", "per-feature PSI vs training distribution"
            )
            self._g_max = registry.gauge(
                "analytics_drift_max_psi", "worst-feature PSI"
            )

    def step(self, poll_timeout_s: float = 0.0) -> int:
        """Consume one poll; score a window when one fills. Returns rows seen."""
        if self.reference is None:
            self.reference = self._reference_builder()
            if self.reference_path:
                try:
                    self.reference.save(self.reference_path)
                except OSError:
                    import logging

                    logging.getLogger(__name__).exception(
                        "drift reference save to %s failed; the baseline "
                        "will NOT survive a restart", self.reference_path)
        records = self._consumer.poll(self.window, poll_timeout_s)
        if not records:
            return 0
        # the router's decoder, so drift windows see exactly the rows the
        # scorer saw (poison pills included, as all-zero rows)
        from ccfd_tpu.router.router import decode_records

        rows, _, _ = decode_records(records)
        if rows.shape[0]:
            self._buf.append(rows)
            self._buffered += rows.shape[0]
        while self._buffered >= self.window:
            allrows = np.concatenate(self._buf, axis=0)
            win, rest = allrows[: self.window], allrows[self.window :]
            self._buf = [rest] if rest.shape[0] else []
            self._buffered = rest.shape[0]
            scores = self.engine.drift(self.reference, win)
            self.windows_scored += 1
            if self._g_psi is not None:
                for i, name in enumerate(FEATURE_NAMES):
                    self._g_psi.set(float(scores[i]), labels={"feature": name})
                self._g_max.set(float(scores.max()))
        return int(rows.shape[0])

    def reset(self) -> None:
        """Re-arm after stop(); called by the supervisor before respawn.
        stop() closed the consumer (to unblock a blocking poll), so
        re-subscribe here — the group's committed offsets make the new
        consumer resume where the old one left off."""
        self._stop.clear()
        if self._consumer_closed:
            self._consumer = self._broker.consumer(self._group, (self._topic,))
            self._consumer_closed = False

    def run(self, interval_s: float = 0.25) -> None:
        while not self._stop.is_set():
            if self.step(poll_timeout_s=interval_s) == 0:
                self._stop.wait(interval_s)

    def stop(self) -> None:
        self._stop.set()
        self._consumer.close()
        self._consumer_closed = True
