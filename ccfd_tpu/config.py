"""12-factor env-var configuration surface.

Keeps the reference's environment-variable contract verbatim so a user of the
reference can drop in this framework with the same manifests:

- router vars: reference deploy/router.yaml:54-70 (BROKER_URL, KAFKA_TOPIC,
  CUSTOMER_NOTIFICATION_TOPIC, CUSTOMER_RESPONSE_TOPIC, KIE_SERVER_URL,
  SELDON_URL, SELDON_ENDPOINT, FRAUD_THRESHOLD) plus optional SELDON_TOKEN
  (reference README.md:447-451).
- KIE-server vars: reference deploy/ccd-service.yaml:54-66 and
  README.md:370-402 (SELDON_TIMEOUT, SELDON_POOL_SIZE, CONFIDENCE_THRESHOLD).
- producer vars: reference deploy/kafka/ProducerDeployment.yaml:77-97
  (topic, s3endpoint, s3bucket, filename, bootstrap).
- notification var: reference deploy/notification-service.yaml:50-52
  (BROKER_URL).

TPU-side knobs (CCFD_*) are new: they configure micro-batching, model choice
and compute dtype for the XLA scorer.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Mapping, Sequence


@dataclasses.dataclass(frozen=True)
class Config:
    # --- bus / topics (reference router.yaml:54-62) ---
    broker_url: str = "inproc://local"
    bus_log_dir: str = ""  # durable segment-log dir (CCFD_BUS_DIR); "" = memory
    bus_fsync: bool = False  # fsync per append (CCFD_BUS_FSYNC=1)
    # per-partition retained-record cap (CCFD_BUS_RETENTION_RECORDS;
    # 0 = retain everything, the pre-round-5 behavior). The broker only
    # deletes records that are BOTH past this cap and below every
    # consumer group's committed offset — the Kafka retention analog of
    # frauddetection_cr.yaml's topic config, strengthened so rewind-based
    # crash recovery can never lose its cut (bus/broker.py).
    bus_retention_records: int = 0
    # per-topic overrides, "topic:cap,topic2:0" (0 = retain everything for
    # that topic) — Kafka's per-topic retention config analog
    # (CCFD_BUS_RETENTION_OVERRIDES)
    bus_retention_overrides: str = ""
    kafka_topic: str = "odh-demo"
    customer_notification_topic: str = "ccd-customer-outgoing"
    customer_response_topic: str = "ccd-customer-response"

    # --- service endpoints (reference router.yaml:63-68) ---
    kie_server_url: str = "inproc://engine"
    seldon_url: str = "inproc://scorer"
    # URL path suffix, as in the reference manifests (router.yaml:65-68) —
    # NOT a model name; model selection is CCFD_MODEL / model_name below.
    seldon_endpoint: str = "api/v0.1/predictions"
    seldon_token: str = ""

    # --- decision thresholds (reference router.yaml:69-70, README.md:395-402) ---
    fraud_threshold: float = 0.5
    rules_file: str = ""  # JSON rule base (CCFD_RULES) -> router/rules.py
    confidence_threshold: float = 1.0

    # --- HTTP client knobs (reference README.md:386-393) ---
    seldon_timeout_ms: int = 5000
    seldon_pool_size: int = 5
    # new: bounded retries on transport failure (reference's only failure
    # knob is the timeout; retries keep the pipeline up across scorer
    # restarts under the supervisor)
    client_retries: int = 2
    # standing network fault plan (CCFD_FAULTS,
    # "edge:latency=50,jitter=20,error=0.1;edge2:blackhole" —
    # runtime/faults.py): degraded-edge injection on the named client
    # edges (scorer/engine/bus/store). "" = no faults. The chaos CR
    # block's `faults` option is the storm-scheduled form of the same
    # syntax.
    faults_spec: str = ""

    # --- producer (reference ProducerDeployment.yaml:88-97) ---
    producer_topic: str = "odh-demo"
    s3_endpoint: str = ""
    s3_bucket: str = "ccdata"
    filename: str = "creditcard.csv"
    bootstrap: str = "odh-message-bus-kafka-brokers:9092"
    # secret-ref pair from the reference's `keysecret`
    # (ProducerDeployment.yaml:78-87, deploy/ceph/s3-secretceph.yaml:4-7)
    access_key_id: str = ""
    secret_access_key: str = ""

    # --- process engine (reference README.md:554-605 semantics) ---
    customer_reply_timeout_s: float = 30.0
    low_amount_threshold: float = 200.0
    low_proba_threshold: float = 0.75

    # --- online retrain (new; BASELINE.json configs[4]) ---
    labels_topic: str = "ccd-labels"
    audit_topic: str = ""  # "" = audit stream off; a topic name enables the
    # engine's jBPM-AuditService-analog lifecycle event stream onto the bus
    retrain_batch: int = 1024
    retrain_min_labels: int = 256

    # --- model lifecycle (lifecycle/; governed rollout of retrained
    # models: shadow -> canary -> gated promotion with auto-rollback) ---
    # paired champion/challenger shadow scores ride this topic
    shadow_topic: str = "ccd-shadow-scores"  # CCFD_LIFECYCLE_SHADOW_TOPIC
    # lineage/audit + candidate checkpoints persistence root; "" keeps the
    # version store in memory (lineage does NOT survive restarts then)
    lifecycle_dir: str = ""  # CCFD_LIFECYCLE_DIR
    # guardrails (lifecycle/controller.py Guardrails; see ARCHITECTURE.md)
    lifecycle_min_labels: int = 128          # CCFD_LIFECYCLE_MIN_LABELS
    lifecycle_min_shadow_rows: int = 1024    # CCFD_LIFECYCLE_MIN_SHADOW_ROWS
    lifecycle_auc_margin: float = 0.01       # CCFD_LIFECYCLE_AUC_MARGIN
    lifecycle_max_alert_delta: float = 0.10  # CCFD_LIFECYCLE_MAX_ALERT_DELTA
    lifecycle_max_psi: float = 0.25          # CCFD_LIFECYCLE_MAX_PSI
    lifecycle_canary_weight: float = 0.10    # CCFD_LIFECYCLE_CANARY_WEIGHT
    lifecycle_canary_min_labels: int = 64    # CCFD_LIFECYCLE_CANARY_MIN_LABELS
    # submissions inside this interval of the last accepted candidate
    # coalesce into it instead of superseding it (anti-livelock pacing
    # for fast retrain loops); 0 accepts every submission
    lifecycle_min_submit_interval_s: float = 30.0  # CCFD_LIFECYCLE_MIN_SUBMIT_INTERVAL_S

    # --- distributed tracing (observability/trace.py) ---
    # tail sampler: probabilistic keep-rate for BORING traces
    # (slow/errored/fraud/degraded traces are always kept). 1.0 keeps
    # everything (tools/trace_report.py), 0.0 keeps only forced traces.
    trace_sample: float = 0.02  # CCFD_TRACE_SAMPLE
    # a trace with any span at/above this duration is always kept
    trace_slow_ms: float = 100.0  # CCFD_TRACE_SLOW_MS

    # --- router fan-out (router/parallel.py) ---
    # worker loops consuming the transaction topic: 1 = the historical
    # single Router; 0 = auto (one worker per bus partition); >1 explicit.
    # Workers split partitions via consumer-group assignment and share one
    # device scorer through a coalescing batcher (CCFD_ROUTER_WORKERS).
    router_workers: int = 1
    # coalesce concurrent workers' sub-batches into one device dispatch
    # (CCFD_ROUTER_COALESCE; on by default — off means each worker
    # dispatches its own batches, which only makes sense for measuring)
    router_coalesce: bool = True

    # --- overload control (runtime/overload.py) ---
    # master switch for the adaptive-admission plane: AIMD in-flight
    # budget + priority-aware shedding on the router, priority-tiered
    # 429 admission on the REST fronts (CCFD_OVERLOAD; 0 disables and
    # restores the static-budget / unbounded-queue semantics everywhere)
    overload_enabled: bool = True
    # scorer-stage latency budget the router's AIMD limit is derived
    # from: observed dispatch latency above it shrinks the in-flight
    # limit multiplicatively, a window below it grows it additively
    overload_target_ms: float = 50.0       # CCFD_OVERLOAD_TARGET_MS
    # serving-stage (REST) latency budget for the admission gate's AIMD
    overload_serve_target_ms: float = 25.0  # CCFD_OVERLOAD_SERVE_TARGET_MS
    # adaptive limit bounds in rows; 0 = auto (min: one router max_batch,
    # max: 4x the initial limit)
    overload_min_inflight: int = 0         # CCFD_OVERLOAD_MIN_INFLIGHT
    overload_max_inflight: int = 0         # CCFD_OVERLOAD_MAX_INFLIGHT
    # CoDel-style bus sojourn target: records older than this (scaled 1x/
    # 2x/4x for bulk/normal/critical priority) drop from the front at
    # poll time. DEFAULT OFF (0): crash recovery legitimately re-drives
    # minutes-old records, and a standing deadline would shed the replay —
    # arm it explicitly for live traffic (CCFD_OVERLOAD_CODEL_TARGET_MS)
    overload_codel_target_ms: float = 0.0
    # serving DynamicBatcher queue sojourn target (same CoDel policy,
    # perf_counter-based so replay-safe); 0 = off
    overload_serve_codel_target_ms: float = 0.0  # CCFD_OVERLOAD_SERVE_CODEL_TARGET_MS
    # serving DynamicBatcher queue bound in rows with priority-aware
    # eviction (arrivals past it 429); 0 = unbounded (historical)
    overload_rest_queue_rows: int = 0      # CCFD_OVERLOAD_REST_QUEUE_ROWS
    # router dispatch watchdog: a scorer dispatch past this deadline is
    # killed and trips the scorer-edge breaker instead of stalling the
    # worker. -1 = auto (SELDON_TIMEOUT on accelerator backends, off on
    # cpu — same resolution as the server-side dispatch deadline); 0 = off
    overload_dispatch_deadline_ms: float = -1.0  # CCFD_OVERLOAD_DISPATCH_DEADLINE_MS

    # --- SLO monitoring (observability/slo.py; CR block `slo:`) ---
    # master switch for the stage profiler + SLO engine (CCFD_SLO; 0
    # disables the profile/burn-rate plane entirely — like CCFD_OVERLOAD
    # it is the emergency kill switch a CR cannot override)
    slo_enabled: bool = True
    # evaluation tick for the supervised SLO service
    slo_interval_s: float = 5.0            # CCFD_SLO_INTERVAL_S
    # latency objectives: "objective fraction of events at/under target"
    slo_e2e_target_ms: float = 50.0        # CCFD_SLO_E2E_TARGET_MS
    slo_rest_target_ms: float = 25.0       # CCFD_SLO_REST_TARGET_MS
    slo_objective: float = 0.99            # CCFD_SLO_OBJECTIVE
    # error-rate objective: counted process-start failures over incoming
    slo_max_error_rate: float = 0.01       # CCFD_SLO_MAX_ERROR_RATE
    # burn-rate windows in seconds: every entry but the last is a FAST
    # window alerting at slo_fast_burn (short confirms long); the last is
    # the slow budget window at burn 1.0 (CCFD_SLO_WINDOWS)
    slo_windows: str = "300,3600,21600"
    slo_fast_burn: float = 14.4            # CCFD_SLO_FAST_BURN
    # REST transport floor for the budget ledger: the r04
    # rest_latency_floor measurement (NativeFront 1x1-row RTT p99,
    # REST_SWEEP/BENCH_r04) — re-measure with tools/rest_sweep.py when
    # the front or host changes (CCFD_SLO_TRANSPORT_FLOOR_MS)
    slo_transport_floor_ms: float = 0.072

    # --- device telemetry (observability/device.py; CR block `device:`) ---
    # master switch for the device & transfer telemetry plane: per-device
    # memory gauges, measured H2D accounting on the scorer staging path,
    # the executable inventory and the /debug/profile capture endpoint
    # (CCFD_DEVICE; 0 is the emergency kill switch — the BudgetLedger's
    # h2d layer then falls back to the fixed reservation)
    device_enabled: bool = True

    # --- incident flight recorder (observability/incident.py; CR block
    # `incident:`) ---
    # master switch for the FlightRecorder + SLO-breach incident bundles
    # (CCFD_INCIDENT; 0 kills the plane — breaches still page, they just
    # stop dumping post-mortem bundles)
    incident_enabled: bool = True
    # periodic ring-snapshot cadence for the supervised recorder service
    incident_interval_s: float = 5.0       # CCFD_INCIDENT_INTERVAL_S
    # bounded snapshot ring depth
    incident_ring: int = 64                # CCFD_INCIDENT_RING
    # bundle persistence dir ("" = bundles held in memory only — still
    # served at /incidents, lost on restart); writes are crash-safe
    # (tmp+rename)
    incident_dir: str = ""                 # CCFD_INCIDENT_DIR

    # --- capacity observatory (observability/capacity.py; CR block
    # `capacity:`) ---
    # master switch for the queueing-model plane: per-stage utilization/
    # headroom/bottleneck fitting, predicted-p99 vs observed, /capacity +
    # /capacity/whatif, and the service-curve regression sentinel
    # (CCFD_CAPACITY; 0 is the emergency kill switch — both endpoints 404
    # and no capacity gauges export)
    capacity_enabled: bool = True
    # fit-window tick for the supervised refresh service
    capacity_interval_s: float = 2.0       # CCFD_CAPACITY_INTERVAL_S
    # persisted service-curve baseline file ("" = in-memory baseline only:
    # the sentinel re-arms from live traffic after a restart); writes ride
    # the PR 13 durability seam (tmp+rename+sha256 sidecar)
    capacity_baseline_file: str = ""       # CCFD_CAPACITY_BASELINE
    # sentinel tolerance as a fractional departure from baseline: 1.0
    # fires past 2x (or under 0.5x) the baseline fitted mean
    capacity_regression_tolerance: float = 1.0  # CCFD_CAPACITY_REGRESSION_TOL
    # samples a stage needs before its baseline is captured
    capacity_min_samples: int = 50         # CCFD_CAPACITY_MIN_SAMPLES

    # --- decision provenance audit (observability/audit.py; CR block
    # `audit:`) ---
    # master switch for the per-transaction DecisionRecord plane: the
    # router stamps one compact record per routed transaction at the
    # route seam, queryable at /decisions/<tx_id> and reconstructable
    # after a crash-restore (CCFD_AUDIT; 0 is the emergency kill switch —
    # no records stamped, both exporter endpoints 404)
    audit_enabled: bool = True
    # segmented append-only log dir ("" = ring only: decisions queryable
    # live but NOT reconstructable across a restart)
    audit_dir: str = ""                    # CCFD_AUDIT_DIR
    # bounded query-ring depth (records; oldest evicted, counted)
    audit_ring: int = 65536                # CCFD_AUDIT_RING
    # log segment rotation size and retained-segment count (the PR 13
    # generation-retention idea applied to an append-only log)
    audit_segment_bytes: int = 4 * 1024 * 1024  # CCFD_AUDIT_SEGMENT_BYTES
    audit_segments: int = 8                # CCFD_AUDIT_SEGMENTS
    # supervised flusher cadence: pending records land as one framed
    # block per tick (a crash loses at most one tick of records — the
    # torn tail truncates and counts at the next bring-up)
    audit_flush_interval_s: float = 0.25   # CCFD_AUDIT_FLUSH_INTERVAL_S

    # --- bulk replay & backtest (replay/; CR block `replay:`) ---
    # master switch: arms feature-row capture at the route seam, the
    # verdict tap and the supervised replay worker (CCFD_REPLAY; off by
    # default — capture grows audit records by ~30 floats each)
    replay_enabled: bool = False
    # rows re-produced per replay batch (one cursor commit per batch —
    # the crash-resume granularity) (CCFD_REPLAY_BATCH)
    replay_batch: int = 256
    # verdict-join wait per production attempt before re-producing the
    # unanswered remainder (CCFD_REPLAY_TIMEOUT_S)
    replay_timeout_s: float = 10.0
    # re-production attempts per batch beyond the first; bulk rows shed
    # under live load come back on the next attempt
    # (CCFD_REPLAY_RETRIES)
    replay_retries: int = 3
    # fraction of the adaptive admission budget bulk/replay work may
    # occupy while a window runs — the zero-live-SLO-impact guarantee
    # (CCFD_REPLAY_BULK_CEILING)
    replay_bulk_ceiling: float = 0.5
    # pacing in rows/second; 0 saturates the bulk share
    # (CCFD_REPLAY_PACING)
    replay_pacing_rows_s: float = 0.0
    # durable-cursor directory ("" = resume disabled: a killed window
    # restarts from its first row) (CCFD_REPLAY_DIR)
    replay_dir: str = ""

    # --- durable-state integrity (runtime/durability.py; CR block
    # `durability:`) ---
    # generations retained per single-file artifact (lineage, recovery
    # cuts, engine snapshots, usertask/drift npz): a corrupt live file
    # quarantines to *.corrupt and the newest verifiable generation
    # serves instead (CCFD_STORAGE_RETAIN; 0 disables retention — reads
    # then fail hard to cold-start on corruption)
    storage_retain: int = 3
    # fsync before every atomic rename (CCFD_STORAGE_FSYNC; 0 trades
    # host-crash durability for write latency — process-crash safety is
    # kept either way)
    storage_fsync: bool = True
    # startup sweep of orphaned *.tmp files a crash mid-write leaves
    # behind (CCFD_STORAGE_SWEEP; counted ccfd_storage_tmp_swept_total)
    storage_sweep: bool = True
    # standing storage-fault plan (CCFD_STORAGE_FAULTS,
    # "bitrot;torn_write:rate=0.5;slow_disk:ms=10" — runtime/faults.py
    # storage faults, injected at the durability seam every persistent
    # writer/reader shares). "" = none. The chaos CR block's
    # `storage_faults` option is the storm-scheduled form.
    storage_faults_spec: str = ""

    # --- device self-healing (runtime/heal.py; CR block `heal:`) ---
    # master switch for the DeviceSupervisor: per-device health state
    # machine (HEALTHY -> SUSPECT -> QUARANTINED -> PROBATION), canary
    # dispatches, the heal ladder and warm re-promotion (CCFD_HEAL; 0 is
    # the emergency kill switch — the router ladder then falls back to
    # breaker-only device gating)
    heal_enabled: bool = True
    # supervision tick (canary cadence while healthy; heal-ladder poll
    # while quarantined)
    heal_interval_s: float = 5.0           # CCFD_HEAL_INTERVAL_S
    # hard deadline for one canary dispatch (rides the PR 6
    # bounded_dispatch watchdog; a hung canary is killed, counted, and
    # counts as a strike)
    heal_canary_deadline_ms: float = 250.0  # CCFD_HEAL_CANARY_DEADLINE_MS
    # consecutive strike-bearing ticks before SUSPECT escalates to
    # QUARANTINED (1 = quarantine on the first bad tick)
    heal_suspect_strikes: int = 2          # CCFD_HEAL_SUSPECT_STRIKES
    # consecutive canary+parity passes PROBATION requires before the warm
    # re-promotion flip returns serving to the device
    heal_probation_canaries: int = 3       # CCFD_HEAL_PROBATION_CANARIES
    # host-vs-device score-parity tolerance for the re-promotion gate
    # (max abs probability delta; bf16-vs-f32 sits well under 0.05)
    heal_parity_tol: float = 0.05          # CCFD_HEAL_PARITY_TOL
    # allocator pressure ratio (bytes_in_use / bytes_limit) treated as
    # OOM-pressure evidence
    heal_oom_ratio: float = 0.92           # CCFD_HEAL_OOM_RATIO
    # serving-stage XLA compiles per second treated as a compile storm
    heal_compile_storm_per_s: float = 2.0  # CCFD_HEAL_COMPILE_STORM_PER_S
    # heal-ladder backoff: jittered exponential from base to cap between
    # attempts (canary retry -> backend reinit -> scorer respawn)
    heal_backoff_base_s: float = 0.5       # CCFD_HEAL_BACKOFF_BASE_S
    heal_backoff_cap_s: float = 30.0       # CCFD_HEAL_BACKOFF_CAP_S
    # flap hysteresis: a re-quarantine inside this window of the last
    # re-promotion starts the backoff ladder deeper each round
    heal_flap_window_s: float = 60.0       # CCFD_HEAL_FLAP_WINDOW_S
    # standing device-fault plan (CCFD_DEVICE_FAULTS,
    # "device_hang:ms=400;put_fail" — runtime/faults.py device faults,
    # injected at the scorer dispatch / staging-put / compile seams).
    # "" = none. The chaos CR block's `device_faults` option is the
    # storm-scheduled form of the same syntax.
    device_faults_spec: str = ""

    # --- fleet serving (fleet/; CR block `fleet:`) ---
    # this process's member name within the fleet ("" = member-<pid>);
    # stamps every heartbeat, fleet gauge and ledger entry
    # (CCFD_FLEET_MEMBER)
    fleet_member: str = ""
    # heartbeat HTTP port (0 = ephemeral; fleets pin real ports so the
    # peer list can be written before any process exists)
    # (CCFD_FLEET_HEARTBEAT_PORT)
    fleet_heartbeat_port: int = 0
    # comma-separated peer heartbeat endpoints,
    # "http://127.0.0.1:7101,http://127.0.0.1:7102" (CCFD_FLEET_PEERS)
    fleet_peers: str = ""
    # membership lease: a member whose last heartbeat is older than this
    # is DEAD to the fleet — its partitions re-adopted (bus fence), its
    # admission share redistributed (CCFD_FLEET_TTL_S)
    fleet_ttl_s: float = 3.0
    # gossip tick: peer heartbeat dial + fleet-actuator cadence
    # (CCFD_FLEET_GOSSIP_INTERVAL_S)
    fleet_gossip_interval_s: float = 0.5
    # fleet-wide admission ceiling, split equally over LIVE members and
    # applied as each member's AIMD budget ceiling; 0 = no fleet bound
    # (each member keeps its own overload max_inflight)
    # (CCFD_FLEET_GLOBAL_MAX_INFLIGHT)
    fleet_global_max_inflight: int = 0
    # bus topic carrying per-transaction route dispositions — the fleet's
    # durable conservation ledger (CCFD_FLEET_LEDGER_TOPIC)
    fleet_ledger_topic: str = "fleet.ledger"

    # --- multi-chip mesh serving (parallel/partition.py; CR block
    # `mesh:`) ---
    # device count for the serving/retrain mesh: 1 = single-device (the
    # historical default), 0 = every local device, N = the first N.
    # With >1 the operator builds the named (data, fsdp, tp) mesh, wraps
    # it in a partitioner and serves data-parallel through the live
    # stack (CCFD_MESH_DEVICES)
    mesh_devices: int = 1
    # fsdp / tensor-parallel axis sizes; the data axis absorbs the
    # remainder (CCFD_MESH_FSDP / CCFD_MESH_TP)
    mesh_fsdp: int = 1
    mesh_tp: int = 1
    # param layout: "replicated" (pure data parallel, the serving
    # default) or "rules" (the model family's regex rule table over
    # fsdp/tp — partition.mlp_rules/seq_rules) (CCFD_MESH_PARAM_PARTITION)
    mesh_param_partition: str = "replicated"
    # sequence-parallel attention for the seq family: none | ring |
    # ulysses — shards attention L over the tp axis (the previously
    # dormant ring_attention flag, now operator-selectable)
    # (CCFD_MESH_SEQ_PARALLEL)
    mesh_seq_parallel: str = "none"

    # --- sequence serving (serving/history.py; CR block `scorer.seq_*`) ---
    # HistoryStore stripe count: per-stripe locks keep ParallelRouter
    # workers from convoying on one global lock (CCFD_SEQ_STRIPES)
    seq_stripes: int = 8
    # async dispatches in flight before the scoring loop blocks on the
    # oldest; 0 restores the synchronous chunk loop (CCFD_SEQ_INFLIGHT)
    seq_inflight: int = 2
    # short-sequence ladder: a row whose post-append history depth fits a
    # bucket dispatches through that (bucket, F) executable instead of
    # padding to full L. OFF by default (empty): short windows attend
    # fewer zero-pad tokens than the full-L graph (no padding mask in
    # the attention), so cold-row scores differ between rungs — arm it
    # explicitly for dispatch-bound deployments where that tradeoff is
    # acceptable (CCFD_SEQ_LEN_BUCKETS, comma-separated, e.g. "1,8")
    seq_len_buckets: Sequence[int] = ()

    # --- TPU scorer knobs (new) ---
    model_name: str = "mlp"
    graph_cr: str = ""  # SeldonDeployment-shaped CR file -> serving/graph.py
    compute_dtype: str = "bfloat16"
    batch_sizes: Sequence[int] = (16, 128, 1024, 4096, 16384)
    batch_deadline_ms: float = 2.0
    batch_workers: int = 4  # overlapped dispatches (device-RTT pipelining)
    dynamic_batching: bool = True  # serving-side request coalescing
    native_front: bool = True  # C++ HTTP front when the toolchain allows
    host_tier_rows: int = -1  # -1 = auto: measured at scorer warmup (host
    # forward rate vs device dispatch RTT, crossover at RTT/2, <=8192;
    # 256 provisionally until warmup runs); 0 = off; >0 = fixed threshold
    dispatch_deadline_ms: float = -1.0  # server-side device-dispatch bound
    # (the reference's SELDON_TIMEOUT applied inside the server): -1 = auto
    # (accelerator backends: seldon_timeout_ms; cpu/mesh: off), 0 = off,
    # >0 = explicit deadline
    # --- fused decision kernel (ops/fused_decision.py, serving/fused.py;
    # CR `scorer.fused_decision`) ---
    # one jitted executable per batch bucket returns (proba, fired rule)
    # together: score, FRAUD_THRESHOLD compare and the vectorizable rule
    # base all on device, ONE transfer back. Off by default: arming it is
    # a routing-semantics statement (device-evaluated rules), even though
    # parity with the staged path is bit-exact (CCFD_FUSED_DECISION)
    fused_decision: bool = False
    # strict = refuse to start (RuntimeError) when the fused plane cannot
    # arm (unvectorizable rules, incompatible scorer) instead of the
    # default warn-and-serve-staged (CCFD_FUSED_DECISION_STRICT)
    fused_decision_strict: bool = False
    serve_host: str = "0.0.0.0"
    serve_port: int = 8000

    def parsed_retention_overrides(self) -> dict[str, int | None]:
        """``"topic:cap,topic2:0"`` -> {topic: cap, topic2: None}; the form
        ``Broker(retention_overrides=)`` takes (0 = retain everything for
        that topic). Malformed entries raise here, at config time, not in
        the broker's append path."""
        out: dict[str, int | None] = {}
        for item in self.bus_retention_overrides.split(","):
            item = item.strip()
            if not item:
                continue
            topic, sep, cap = item.partition(":")
            if not sep or not topic:
                raise ValueError(
                    f"CCFD_BUS_RETENTION_OVERRIDES entry {item!r}: "
                    "expected topic:records")
            n = int(cap)
            out[topic] = n if n > 0 else None
        return out

    def scorer_dispatch_deadline_ms(self) -> float | None:
        """The value serving code passes to ``Scorer(dispatch_deadline_ms=)``.

        Explicit (>= 0) wins; auto (-1) resolves to the SELDON_TIMEOUT bound
        so the server-side deadline tracks the client-side knob, and returns
        it as a number so a programmatically-built Config is honored (the
        scorer still disables the guard itself on cpu/mesh backends when
        handed None — which only happens for scorers built without a Config).
        """
        if self.dispatch_deadline_ms >= 0:
            return self.dispatch_deadline_ms
        import jax

        if jax.default_backend() in ("cpu",):
            return 0.0
        return float(self.seldon_timeout_ms)

    @staticmethod
    def from_env(env: Mapping[str, str] | None = None) -> "Config":
        e = dict(os.environ if env is None else env)
        sizes = e.get("CCFD_BATCH_SIZES", "")
        seq_lb = e.get("CCFD_SEQ_LEN_BUCKETS", "")
        return Config(
            mesh_devices=int(
                e.get("CCFD_MESH_DEVICES", str(Config.mesh_devices))),
            mesh_fsdp=int(e.get("CCFD_MESH_FSDP", str(Config.mesh_fsdp))),
            mesh_tp=int(e.get("CCFD_MESH_TP", str(Config.mesh_tp))),
            mesh_param_partition=e.get(
                "CCFD_MESH_PARAM_PARTITION", Config.mesh_param_partition),
            mesh_seq_parallel=e.get(
                "CCFD_MESH_SEQ_PARALLEL", Config.mesh_seq_parallel),
            seq_stripes=int(e.get("CCFD_SEQ_STRIPES", str(Config.seq_stripes))),
            seq_inflight=int(
                e.get("CCFD_SEQ_INFLIGHT", str(Config.seq_inflight))
            ),
            seq_len_buckets=(
                tuple(int(s) for s in seq_lb.split(",") if s.strip())
                if seq_lb else Config.seq_len_buckets
            ),
            broker_url=e.get("BROKER_URL", Config.broker_url),
            bus_log_dir=e.get("CCFD_BUS_DIR", Config.bus_log_dir),
            bus_fsync=e.get("CCFD_BUS_FSYNC", "") in ("1", "true", "yes"),
            bus_retention_records=int(
                e.get("CCFD_BUS_RETENTION_RECORDS",
                      Config.bus_retention_records)
            ),
            bus_retention_overrides=e.get(
                "CCFD_BUS_RETENTION_OVERRIDES",
                Config.bus_retention_overrides,
            ),
            kafka_topic=e.get("KAFKA_TOPIC", Config.kafka_topic),
            customer_notification_topic=e.get(
                "CUSTOMER_NOTIFICATION_TOPIC", Config.customer_notification_topic
            ),
            customer_response_topic=e.get(
                "CUSTOMER_RESPONSE_TOPIC", Config.customer_response_topic
            ),
            kie_server_url=e.get("KIE_SERVER_URL", Config.kie_server_url),
            seldon_url=e.get("SELDON_URL", Config.seldon_url),
            seldon_endpoint=e.get("SELDON_ENDPOINT", Config.seldon_endpoint),
            seldon_token=e.get("SELDON_TOKEN", Config.seldon_token),
            fraud_threshold=float(e.get("FRAUD_THRESHOLD", str(Config.fraud_threshold))),
            rules_file=e.get("CCFD_RULES", Config.rules_file),
            confidence_threshold=float(
                e.get("CONFIDENCE_THRESHOLD", str(Config.confidence_threshold))
            ),
            seldon_timeout_ms=int(e.get("SELDON_TIMEOUT", str(Config.seldon_timeout_ms))),
            dispatch_deadline_ms=float(
                e.get("CCFD_DISPATCH_DEADLINE_MS", str(Config.dispatch_deadline_ms))
            ),
            seldon_pool_size=int(e.get("SELDON_POOL_SIZE", str(Config.seldon_pool_size))),
            client_retries=int(e.get("CCFD_CLIENT_RETRIES", str(Config.client_retries))),
            faults_spec=e.get("CCFD_FAULTS", Config.faults_spec),
            producer_topic=e.get("topic", Config.producer_topic),
            s3_endpoint=e.get("s3endpoint", Config.s3_endpoint),
            s3_bucket=e.get("s3bucket", Config.s3_bucket),
            filename=e.get("filename", Config.filename),
            bootstrap=e.get("bootstrap", Config.bootstrap),
            access_key_id=e.get("ACCESS_KEY_ID", Config.access_key_id),
            secret_access_key=e.get("SECRET_ACCESS_KEY", Config.secret_access_key),
            customer_reply_timeout_s=float(
                e.get("CCFD_REPLY_TIMEOUT_S", str(Config.customer_reply_timeout_s))
            ),
            low_amount_threshold=float(
                e.get("CCFD_LOW_AMOUNT", str(Config.low_amount_threshold))
            ),
            low_proba_threshold=float(
                e.get("CCFD_LOW_PROBA", str(Config.low_proba_threshold))
            ),
            labels_topic=e.get("CCFD_LABELS_TOPIC", Config.labels_topic),
            audit_topic=e.get("CCFD_AUDIT_TOPIC", Config.audit_topic),
            retrain_batch=int(e.get("CCFD_RETRAIN_BATCH", str(Config.retrain_batch))),
            retrain_min_labels=int(
                e.get("CCFD_RETRAIN_MIN_LABELS", str(Config.retrain_min_labels))
            ),
            shadow_topic=e.get(
                "CCFD_LIFECYCLE_SHADOW_TOPIC", Config.shadow_topic
            ),
            lifecycle_dir=e.get("CCFD_LIFECYCLE_DIR", Config.lifecycle_dir),
            lifecycle_min_labels=int(
                e.get("CCFD_LIFECYCLE_MIN_LABELS",
                      str(Config.lifecycle_min_labels))
            ),
            lifecycle_min_shadow_rows=int(
                e.get("CCFD_LIFECYCLE_MIN_SHADOW_ROWS",
                      str(Config.lifecycle_min_shadow_rows))
            ),
            lifecycle_auc_margin=float(
                e.get("CCFD_LIFECYCLE_AUC_MARGIN",
                      str(Config.lifecycle_auc_margin))
            ),
            lifecycle_max_alert_delta=float(
                e.get("CCFD_LIFECYCLE_MAX_ALERT_DELTA",
                      str(Config.lifecycle_max_alert_delta))
            ),
            lifecycle_max_psi=float(
                e.get("CCFD_LIFECYCLE_MAX_PSI", str(Config.lifecycle_max_psi))
            ),
            lifecycle_canary_weight=float(
                e.get("CCFD_LIFECYCLE_CANARY_WEIGHT",
                      str(Config.lifecycle_canary_weight))
            ),
            lifecycle_canary_min_labels=int(
                e.get("CCFD_LIFECYCLE_CANARY_MIN_LABELS",
                      str(Config.lifecycle_canary_min_labels))
            ),
            lifecycle_min_submit_interval_s=float(
                e.get("CCFD_LIFECYCLE_MIN_SUBMIT_INTERVAL_S",
                      str(Config.lifecycle_min_submit_interval_s))
            ),
            slo_enabled=e.get("CCFD_SLO", "1").strip().lower()
            not in ("0", "false", "no", "off"),
            heal_enabled=e.get("CCFD_HEAL", "1").strip().lower()
            not in ("0", "false", "no", "off"),
            heal_interval_s=float(
                e.get("CCFD_HEAL_INTERVAL_S", str(Config.heal_interval_s))
            ),
            heal_canary_deadline_ms=float(
                e.get("CCFD_HEAL_CANARY_DEADLINE_MS",
                      str(Config.heal_canary_deadline_ms))
            ),
            heal_suspect_strikes=int(
                e.get("CCFD_HEAL_SUSPECT_STRIKES",
                      str(Config.heal_suspect_strikes))
            ),
            heal_probation_canaries=int(
                e.get("CCFD_HEAL_PROBATION_CANARIES",
                      str(Config.heal_probation_canaries))
            ),
            heal_parity_tol=float(
                e.get("CCFD_HEAL_PARITY_TOL", str(Config.heal_parity_tol))
            ),
            heal_oom_ratio=float(
                e.get("CCFD_HEAL_OOM_RATIO", str(Config.heal_oom_ratio))
            ),
            heal_compile_storm_per_s=float(
                e.get("CCFD_HEAL_COMPILE_STORM_PER_S",
                      str(Config.heal_compile_storm_per_s))
            ),
            heal_backoff_base_s=float(
                e.get("CCFD_HEAL_BACKOFF_BASE_S",
                      str(Config.heal_backoff_base_s))
            ),
            heal_backoff_cap_s=float(
                e.get("CCFD_HEAL_BACKOFF_CAP_S",
                      str(Config.heal_backoff_cap_s))
            ),
            heal_flap_window_s=float(
                e.get("CCFD_HEAL_FLAP_WINDOW_S",
                      str(Config.heal_flap_window_s))
            ),
            device_faults_spec=e.get("CCFD_DEVICE_FAULTS",
                                     Config.device_faults_spec),
            fleet_member=e.get("CCFD_FLEET_MEMBER", Config.fleet_member),
            fleet_heartbeat_port=int(
                e.get("CCFD_FLEET_HEARTBEAT_PORT",
                      str(Config.fleet_heartbeat_port))
            ),
            fleet_peers=e.get("CCFD_FLEET_PEERS", Config.fleet_peers),
            fleet_ttl_s=float(
                e.get("CCFD_FLEET_TTL_S", str(Config.fleet_ttl_s))
            ),
            fleet_gossip_interval_s=float(
                e.get("CCFD_FLEET_GOSSIP_INTERVAL_S",
                      str(Config.fleet_gossip_interval_s))
            ),
            fleet_global_max_inflight=int(
                e.get("CCFD_FLEET_GLOBAL_MAX_INFLIGHT",
                      str(Config.fleet_global_max_inflight))
            ),
            fleet_ledger_topic=e.get("CCFD_FLEET_LEDGER_TOPIC",
                                     Config.fleet_ledger_topic),
            audit_enabled=e.get("CCFD_AUDIT", "1").strip().lower()
            not in ("0", "false", "no", "off"),
            audit_dir=e.get("CCFD_AUDIT_DIR", Config.audit_dir),
            audit_ring=int(e.get("CCFD_AUDIT_RING", str(Config.audit_ring))),
            audit_segment_bytes=int(
                e.get("CCFD_AUDIT_SEGMENT_BYTES",
                      str(Config.audit_segment_bytes))
            ),
            audit_segments=int(
                e.get("CCFD_AUDIT_SEGMENTS", str(Config.audit_segments))
            ),
            audit_flush_interval_s=float(
                e.get("CCFD_AUDIT_FLUSH_INTERVAL_S",
                      str(Config.audit_flush_interval_s))
            ),
            replay_enabled=e.get("CCFD_REPLAY", "0").strip().lower()
            in ("1", "true", "yes", "on"),
            replay_batch=int(
                e.get("CCFD_REPLAY_BATCH", str(Config.replay_batch))
            ),
            replay_timeout_s=float(
                e.get("CCFD_REPLAY_TIMEOUT_S", str(Config.replay_timeout_s))
            ),
            replay_retries=int(
                e.get("CCFD_REPLAY_RETRIES", str(Config.replay_retries))
            ),
            replay_bulk_ceiling=float(
                e.get("CCFD_REPLAY_BULK_CEILING",
                      str(Config.replay_bulk_ceiling))
            ),
            replay_pacing_rows_s=float(
                e.get("CCFD_REPLAY_PACING", str(Config.replay_pacing_rows_s))
            ),
            replay_dir=e.get("CCFD_REPLAY_DIR", Config.replay_dir),
            storage_retain=int(
                e.get("CCFD_STORAGE_RETAIN", str(Config.storage_retain))
            ),
            storage_fsync=e.get("CCFD_STORAGE_FSYNC", "1").strip().lower()
            not in ("0", "false", "no", "off"),
            storage_sweep=e.get("CCFD_STORAGE_SWEEP", "1").strip().lower()
            not in ("0", "false", "no", "off"),
            storage_faults_spec=e.get("CCFD_STORAGE_FAULTS",
                                      Config.storage_faults_spec),
            device_enabled=e.get("CCFD_DEVICE", "1").strip().lower()
            not in ("0", "false", "no", "off"),
            incident_enabled=e.get("CCFD_INCIDENT", "1").strip().lower()
            not in ("0", "false", "no", "off"),
            incident_interval_s=float(
                e.get("CCFD_INCIDENT_INTERVAL_S",
                      str(Config.incident_interval_s))
            ),
            incident_ring=int(
                e.get("CCFD_INCIDENT_RING", str(Config.incident_ring))
            ),
            incident_dir=e.get("CCFD_INCIDENT_DIR", Config.incident_dir),
            capacity_enabled=e.get("CCFD_CAPACITY", "1").strip().lower()
            not in ("0", "false", "no", "off"),
            capacity_interval_s=float(
                e.get("CCFD_CAPACITY_INTERVAL_S",
                      str(Config.capacity_interval_s))
            ),
            capacity_baseline_file=e.get("CCFD_CAPACITY_BASELINE",
                                         Config.capacity_baseline_file),
            capacity_regression_tolerance=float(
                e.get("CCFD_CAPACITY_REGRESSION_TOL",
                      str(Config.capacity_regression_tolerance))
            ),
            capacity_min_samples=int(
                e.get("CCFD_CAPACITY_MIN_SAMPLES",
                      str(Config.capacity_min_samples))
            ),
            slo_interval_s=float(
                e.get("CCFD_SLO_INTERVAL_S", str(Config.slo_interval_s))
            ),
            slo_e2e_target_ms=float(
                e.get("CCFD_SLO_E2E_TARGET_MS",
                      str(Config.slo_e2e_target_ms))
            ),
            slo_rest_target_ms=float(
                e.get("CCFD_SLO_REST_TARGET_MS",
                      str(Config.slo_rest_target_ms))
            ),
            slo_objective=float(
                e.get("CCFD_SLO_OBJECTIVE", str(Config.slo_objective))
            ),
            slo_max_error_rate=float(
                e.get("CCFD_SLO_MAX_ERROR_RATE",
                      str(Config.slo_max_error_rate))
            ),
            slo_windows=e.get("CCFD_SLO_WINDOWS", Config.slo_windows),
            slo_fast_burn=float(
                e.get("CCFD_SLO_FAST_BURN", str(Config.slo_fast_burn))
            ),
            slo_transport_floor_ms=float(
                e.get("CCFD_SLO_TRANSPORT_FLOOR_MS",
                      str(Config.slo_transport_floor_ms))
            ),
            trace_sample=float(
                e.get("CCFD_TRACE_SAMPLE", str(Config.trace_sample))
            ),
            trace_slow_ms=float(
                e.get("CCFD_TRACE_SLOW_MS", str(Config.trace_slow_ms))
            ),
            router_workers=int(
                e.get("CCFD_ROUTER_WORKERS", str(Config.router_workers))
            ),
            router_coalesce=e.get("CCFD_ROUTER_COALESCE", "1").strip().lower()
            not in ("0", "false", "no", "off"),
            overload_enabled=e.get("CCFD_OVERLOAD", "1").strip().lower()
            not in ("0", "false", "no", "off"),
            overload_target_ms=float(
                e.get("CCFD_OVERLOAD_TARGET_MS",
                      str(Config.overload_target_ms))
            ),
            overload_serve_target_ms=float(
                e.get("CCFD_OVERLOAD_SERVE_TARGET_MS",
                      str(Config.overload_serve_target_ms))
            ),
            overload_min_inflight=int(
                e.get("CCFD_OVERLOAD_MIN_INFLIGHT",
                      str(Config.overload_min_inflight))
            ),
            overload_max_inflight=int(
                e.get("CCFD_OVERLOAD_MAX_INFLIGHT",
                      str(Config.overload_max_inflight))
            ),
            overload_codel_target_ms=float(
                e.get("CCFD_OVERLOAD_CODEL_TARGET_MS",
                      str(Config.overload_codel_target_ms))
            ),
            overload_serve_codel_target_ms=float(
                e.get("CCFD_OVERLOAD_SERVE_CODEL_TARGET_MS",
                      str(Config.overload_serve_codel_target_ms))
            ),
            overload_rest_queue_rows=int(
                e.get("CCFD_OVERLOAD_REST_QUEUE_ROWS",
                      str(Config.overload_rest_queue_rows))
            ),
            overload_dispatch_deadline_ms=float(
                e.get("CCFD_OVERLOAD_DISPATCH_DEADLINE_MS",
                      str(Config.overload_dispatch_deadline_ms))
            ),
            model_name=e.get("CCFD_MODEL", Config.model_name),
            graph_cr=e.get("CCFD_GRAPH_CR", Config.graph_cr),
            compute_dtype=e.get("CCFD_DTYPE", Config.compute_dtype),
            batch_sizes=tuple(int(s) for s in sizes.split(",")) if sizes else Config.batch_sizes,
            batch_deadline_ms=float(
                e.get("CCFD_BATCH_DEADLINE_MS", str(Config.batch_deadline_ms))
            ),
            batch_workers=int(
                e.get("CCFD_BATCH_WORKERS", str(Config.batch_workers))
            ),
            dynamic_batching=e.get("CCFD_DYNAMIC_BATCHING", "1").strip().lower()
            not in ("0", "false", "no", "off"),
            native_front=e.get("CCFD_NATIVE_FRONT", "1").strip().lower()
            not in ("0", "false", "no", "off"),
            host_tier_rows=int(
                e.get("CCFD_HOST_TIER_ROWS", str(Config.host_tier_rows))
            ),
            fused_decision=e.get("CCFD_FUSED_DECISION", "0").strip().lower()
            in ("1", "true", "yes", "on"),
            fused_decision_strict=e.get(
                "CCFD_FUSED_DECISION_STRICT", "0").strip().lower()
            in ("1", "true", "yes", "on"),
            serve_host=e.get("CCFD_SERVE_HOST", Config.serve_host),
            serve_port=int(e.get("CCFD_SERVE_PORT", str(Config.serve_port))),
        )
