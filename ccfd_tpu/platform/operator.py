"""Platform operator: CR-shaped spec -> running pipeline, in run-book order.

The reference is deployed by an OpenDataHub operator CR whose spec toggles
each platform component (Seldon, Kafka, monitoring, notebooks — reference
deploy/frauddetection_cr.yaml:1-89) followed by a 600-line run-book whose
step order is a dependency sort (reference README.md:44-537; SURVEY.md §3 D:
project → operator → Kafka → Ceph/S3 → model → data → KIE → notification →
router → producer → monitoring). This module is both: a declarative spec
(`PlatformSpec`, loadable from a CR-shaped YAML) and the operator that
brings components up in that topological order with readiness gates between
steps, running every long-lived service under the runtime Supervisor
(restart-on-crash) with health probes and a single Prometheus exporter.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
from typing import Any, Mapping

from ccfd_tpu.config import Config


@dataclasses.dataclass(frozen=True)
class ComponentSpec:
    enabled: bool = True
    options: Mapping[str, Any] = dataclasses.field(default_factory=dict)

    def opt(self, key: str, default: Any = None) -> Any:
        return self.options.get(key, default)


_COMPONENTS = (
    "store",      # Ceph/S3 analog (L0)
    "bus",        # Strimzi Kafka analog (L2)
    "scorer",     # Seldon model serving (L4)
    "engine",     # KIE server (L5)
    "notify",     # notification service (L6)
    "router",     # Camel router (L3)
    "producer",   # Kafka producer (L1) — one-shot job semantics
    "retrain",    # online retrain (new; BASELINE.json configs[4])
    "investigator",  # investigator simulation working the task queue
                  # (the reference demo's Business Central humans,
                  # README.md:547-581) — trains the user-task model
    "analytics",  # batch analytics + drift (JupyterHub/Spark analog,
                  # reference frauddetection_cr.yaml:7-53)
    "monitoring", # Prometheus exporter (L7)
    "health",     # runtime probes (platform)
    "chaos",      # seeded fault injection (new; no reference analog)
    "tracing",    # distributed tracing + tail sampler (new; round 7)
    "lifecycle",  # model lifecycle: shadow -> canary -> gated promotion
                  # with auto-rollback (new; round 9, lifecycle/)
    "overload",   # overload control: adaptive AIMD admission, priority-
                  # aware shedding, REST 429s (new; runtime/overload.py)
    "slo",        # stage profiler + SLO engine: queueing/service/dispatch
                  # decomposition, burn-rate monitoring, budget ledger
                  # (new; observability/profile.py, observability/slo.py)
    "device",     # device & transfer telemetry: per-device memory gauges,
                  # measured H2D accounting, executable inventory,
                  # /debug/profile capture (new; observability/device.py)
    "incident",   # SLO-breach incident flight recorder: snapshot ring +
                  # schema-validated post-mortem bundles served at
                  # /incidents (new; observability/incident.py)
    "heal",       # device self-healing: per-device health state machine,
                  # canary dispatches, quarantine -> heal ladder -> warm
                  # re-promotion (new; runtime/heal.py)
    "mesh",       # multi-chip partitioning layer: named (data, fsdp, tp)
                  # mesh + partitioner for data-parallel sharded serving
                  # and donated sharded retrain (new; parallel/partition.py;
                  # armed when devices > 1)
    "durability", # durable-state integrity plane: checksummed artifacts,
                  # quarantine + last-good recovery, orphan-tmp sweep,
                  # rules-tier pin when nothing verifies (new;
                  # runtime/durability.py)
    "audit",      # decision provenance plane: one DecisionRecord per
                  # routed transaction stamped at the route seam, ring +
                  # segmented crash-safe log, /decisions endpoints (new;
                  # observability/audit.py)
    "fleet",      # multi-host fleet plane: heartbeat gossip membership,
                  # fleet-wide admission shares, champion-parity
                  # quarantine, per-tx conservation ledger over the
                  # SHARED bus (new; fleet/ — one member per process,
                  # processes spawned by fleet/supervisor.py)
    "replay",     # bulk replay & backtest plane: re-score recorded audit
                  # windows through the live stack under bulk admission,
                  # verdict-parity conservation with classified
                  # divergences, crash-resumable cursor (new; replay/)
    "capacity",   # capacity observatory: queueing model fitted over the
                  # live stage profile — predicted p50/p99, bottleneck
                  # attribution, headroom, what-if evaluation, and a
                  # service-curve regression sentinel (new;
                  # observability/capacity.py)
)


@dataclasses.dataclass(frozen=True)
class PlatformSpec:
    components: Mapping[str, ComponentSpec]
    cfg: Config

    @staticmethod
    def from_cr(cr: Mapping[str, Any], cfg: Config | None = None) -> "PlatformSpec":
        """Parse a CR-shaped mapping: top-level ``spec`` holds one block per
        component (the frauddetection_cr.yaml shape), each with ``enabled``
        plus free-form options."""
        spec = cr.get("spec", cr)
        comps: dict[str, ComponentSpec] = {}
        for name in _COMPONENTS:
            block = spec.get(name, {})
            if isinstance(block, bool):
                block = {"enabled": block}
            comps[name] = ComponentSpec(
                # absent blocks default on, EXCEPT: producer/store (traffic
                # and data sources are explicit choices), chaos (fault
                # injection is opt-in), the investigator simulation
                # (a real deployment has real humans on the console), and
                # fleet (a single-process platform is the default shape)
                enabled=bool(
                    block.get(
                        "enabled",
                        name not in ("producer", "store", "chaos",
                                     "investigator", "fleet", "replay"),
                    )
                ),
                options={k: v for k, v in block.items() if k != "enabled"},
            )
        return PlatformSpec(components=comps, cfg=cfg or Config.from_env())

    @staticmethod
    def from_yaml(path: str, cfg: Config | None = None) -> "PlatformSpec":
        import yaml

        with open(path) as f:
            return PlatformSpec.from_cr(yaml.safe_load(f) or {}, cfg=cfg)

    def component(self, name: str) -> ComponentSpec:
        return self.components.get(name, ComponentSpec(enabled=False))


class Platform:
    """Brings a PlatformSpec up/down; owns every component's lifecycle."""

    def __init__(self, spec: PlatformSpec):
        self.spec = spec
        self.cfg = spec.cfg
        self.registries: dict[str, Any] = {}
        self.supervisor = None
        self.broker = None
        self.scorer = None
        self.engine = None
        self.usertask_model = None
        self.engine_server = None
        self.engine_port = None
        self.store_server = None
        self.prediction_server = None
        self.prediction_host = "127.0.0.1"
        self.prediction_port = 0
        self.exporter = None
        self.health_server = None
        self.chaos = None
        self.fault_plan = None  # runtime/faults.FaultPlan when configured
        self.trace_sink = None  # observability/trace.SpanSink when enabled
        self.profiler = None    # observability/profile.StageProfiler
        self.slo = None         # observability/slo.SLOEngine when enabled
        self.device = None      # observability/device.DeviceTelemetry
        self.recorder = None    # observability/incident.FlightRecorder
        self.capacity = None    # observability/capacity.CapacityModel
        self.heal = None        # runtime/heal.DeviceSupervisor
        self.mesh = None        # jax.sharding.Mesh when mesh serving armed
        self.partitioner = None  # parallel/partition.Partitioner
        self.device_fault_plan = None  # runtime/faults.DeviceFaultPlan
        self._device_storm_driven = False  # ChaosMonkey owns its duty cycle
        self.storage_fault_plan = None  # runtime/faults.StorageFaultPlan
        self._storage_storm_driven = False
        self.storage_gate = None  # runtime/durability.StoragePinGate
        self.audit = None       # observability/audit.AuditLog when enabled
        self.fleet = None       # fleet/member.FleetMember when enabled
        self.replay = None      # replay/service.ReplayService when enabled
        self.replay_tap = None  # replay/service.ReplayVerdictTap (replay on)
        self.fleet_ledger = None  # fleet/ledger.FleetLedgerTap (fleet on)
        self.fused_decision = None  # serving/fused.FusedDecisionScorer
        self._overload = None   # runtime/overload.OverloadControl (router)
        self.lifecycle = None   # lifecycle.LifecycleController when enabled
        self.router = None
        self.investigator = None
        self.recovery = None  # CheckpointCoordinator when crash_recovery on
        self._engine_factory = None
        self._producer_done = threading.Event()
        self._broker_is_client = False  # bus.url: RemoteBroker/adapter
        self._up = False

    # -- bring-up, in the run-book's dependency order ---------------------
    def up(self, wait_ready_s: float = 30.0) -> "Platform":
        from ccfd_tpu.runtime.supervisor import Supervisor

        if self._up:
            return self
        spec, cfg = self.spec, self.cfg
        self.supervisor = Supervisor()

        # 0. network fault plan (runtime/faults.py): CR `chaos.faults`
        # (ONLY when the chaos component is enabled — chaos is always
        # opt-in, and a disabled block must not leave standing faults
        # wired into production edges) or the CCFD_FAULTS env (its own
        # explicit opt-in). A standing (env) plan starts ACTIVE; a
        # storm-scheduled plan (chaos.fault_interval_s) starts inactive
        # and the ChaosMonkey drives its duty cycle. Edges wire up as
        # each component builds below.
        chaos_spec = spec.component("chaos")
        fault_text = (chaos_spec.opt("faults", "")
                      if chaos_spec.enabled else "") or cfg.faults_spec
        storm_interval = (chaos_spec.opt("fault_interval_s", None)
                          if chaos_spec.enabled else None)
        if fault_text:
            from ccfd_tpu.runtime.faults import FaultPlan

            self.fault_plan = FaultPlan.from_string(
                fault_text,
                seed=int(chaos_spec.opt("seed", 0)),
                active=storm_interval is None,
            )
        # device faults (runtime/faults.py DeviceFaultPlan): same opt-in
        # rules as edge faults — CR `chaos.device_faults` (chaos enabled)
        # or the CCFD_DEVICE_FAULTS env. Installed process-wide because
        # the seams (scorer dispatch / staging put / telemetry overlay)
        # sit inside helpers no injector proxy can wrap.
        cr_dev_text = (chaos_spec.opt("device_faults", "")
                       if chaos_spec.enabled else "")
        dev_fault_text = cr_dev_text or cfg.device_faults_spec
        # only a CR-configured plan under a storm interval is duty-cycled
        # by the ChaosMonkey; a standing CCFD_DEVICE_FAULTS env plan stays
        # ACTIVE — an unrelated edge-storm schedule must not disarm it
        self._device_storm_driven = bool(cr_dev_text) and \
            storm_interval is not None
        if dev_fault_text:
            from ccfd_tpu.runtime.faults import (
                DeviceFaultPlan,
                install_device_faults,
            )

            self.device_fault_plan = DeviceFaultPlan.from_string(
                dev_fault_text,
                seed=int(chaos_spec.opt("seed", 0)),
                active=not self._device_storm_driven,
            )
            install_device_faults(self.device_fault_plan)
        # storage faults (runtime/faults.py StorageFaultPlan): same opt-in
        # and storm rules — CR `chaos.storage_faults` or CCFD_STORAGE_FAULTS.
        # Installed process-wide: the seam (durability.atomic_write_bytes)
        # sits inside constructors and module helpers.
        cr_sto_text = (chaos_spec.opt("storage_faults", "")
                       if chaos_spec.enabled else "")
        sto_fault_text = cr_sto_text or cfg.storage_faults_spec
        self._storage_storm_driven = bool(cr_sto_text) and \
            storm_interval is not None
        if sto_fault_text:
            from ccfd_tpu.runtime.faults import (
                StorageFaultPlan,
                install_storage_faults,
            )

            self.storage_fault_plan = StorageFaultPlan.from_string(
                sto_fault_text,
                seed=int(chaos_spec.opt("seed", 0)),
                active=not self._storage_storm_driven,
            )
            install_storage_faults(self.storage_fault_plan)

        # 0b. durable-state integrity plane (runtime/durability.py): the
        # CR `durability:` block overlays the CCFD_STORAGE_* knobs, the
        # ccfd_storage_* counters land in a scraped registry, and the
        # StoragePinGate (rules-tier pin when NO params generation
        # verifies) is created here so the lifecycle controller (step 7)
        # can arm it before the router (step 6c... order: router then
        # heal compose it into the heal-gate seam).
        from ccfd_tpu.runtime import durability

        dur_spec = spec.component("durability")
        if dur_spec.enabled:
            durability.configure(
                retain=int(dur_spec.opt("retain", cfg.storage_retain)),
                fsync=bool(dur_spec.opt("fsync", cfg.storage_fsync)),
                sweep=bool(dur_spec.opt("sweep", cfg.storage_sweep)),
            )
            durability.bind_registry(self._registry("storage"))
            self.storage_gate = durability.StoragePinGate(
                registry=self._registry("storage"))
        else:
            # legacy mode: no retention copies, no sweep, no rules pin —
            # reads still verify frames they find (integrity itself has
            # no off switch; a checksum mismatch is never servable)
            durability.configure(retain=0, sweep=False)

        # 0a. overload control (runtime/overload.py): the CR `overload:`
        # block overlays the CCFD_OVERLOAD_* env KNOBS once, here, so the
        # scorer's REST admission gate (built in step 3) and the router's
        # adaptive budget (step 6) read the same resolved values.
        # Precedence for the on/off switch: either side can DISABLE the
        # plane (CR `enabled: false` OR env CCFD_OVERLOAD=0) — the env
        # form is the emergency kill switch and a CR cannot override it
        # (an absent CR block is indistinguishable from a default-enabled
        # one, so "CR re-enables over env" is not expressible anyway).
        ov_spec = spec.component("overload")
        ov_overrides: dict[str, Any] = {}
        if not ov_spec.enabled:
            ov_overrides["overload_enabled"] = False
        else:
            for opt, field in (
                ("target_ms", "overload_target_ms"),
                ("serve_target_ms", "overload_serve_target_ms"),
                ("min_inflight", "overload_min_inflight"),
                ("max_inflight", "overload_max_inflight"),
                ("codel_target_ms", "overload_codel_target_ms"),
                ("serve_codel_target_ms", "overload_serve_codel_target_ms"),
                ("rest_queue_rows", "overload_rest_queue_rows"),
                ("dispatch_deadline_ms", "overload_dispatch_deadline_ms"),
            ):
                if ov_spec.opt(opt) is not None:
                    ov_overrides[field] = type(getattr(cfg, field))(
                        ov_spec.opt(opt))
        if ov_overrides:
            self.cfg = cfg = dataclasses.replace(cfg, **ov_overrides)

        # 0b. distributed tracing (observability/trace.py): ONE tail-
        # sampling span sink shared by every component tracer; the tracers
        # themselves are built per component below, registry-injected so
        # span latency lands on the SAME scraped registries the exporter
        # serves (the old utils/tracing global wrote to a private registry
        # nothing scraped). Sampler knobs: CR `tracing.sample`/`slow_ms`
        # over the CCFD_TRACE_SAMPLE / CCFD_TRACE_SLOW_MS env defaults.
        tr_spec = spec.component("tracing")
        if tr_spec.enabled:
            from ccfd_tpu.observability.trace import SpanSink

            self.trace_sink = SpanSink(
                sample=float(tr_spec.opt("sample", cfg.trace_sample)),
                slow_s=float(tr_spec.opt("slow_ms", cfg.trace_slow_ms)) / 1e3,
                max_retained=int(tr_spec.opt("max_retained", 256)),
                registry=self._registry("tracing"),
            )
            if tr_spec.opt("json_logs", True):
                # trace-correlated structured logs for the framework's own
                # logger namespace (observability/slog.py); the embedding
                # application's root logger is left alone
                from ccfd_tpu.observability import slog

                slog.configure("platform")

        # 0c. stage profiler (observability/profile.py): ONE profiler for
        # the whole platform, fed directly by the router (bus queue,
        # decode/route service, scorer dispatch) and the serving batcher
        # (REST wait/dispatch), plus span ingestion off the tail sampler
        # for the stages with no hot-path feed (producer, engine REST,
        # notify, serving). Exported live at the exporter's /profile —
        # the machine-readable planner input (ROADMAP item 3). The SLO
        # engine over it is built in step 7c, once the components whose
        # histograms it reads exist. CCFD_SLO=0 (or CR slo.enabled:
        # false) disables the whole plane.
        slo_spec = spec.component("slo")
        if slo_spec.enabled and cfg.slo_enabled:
            from ccfd_tpu.observability.profile import StageProfiler

            self.profiler = StageProfiler(
                registry=self._registry("slo"),
                overload_registry=self._registry("router"),
            )
            if self.trace_sink is not None:
                self.trace_sink.add_listener(self.profiler.on_span)
            if bool(slo_spec.opt("compile_events", True)):
                self.profiler.arm_compile_listener()

        # 0d. device & transfer telemetry (observability/device.py): ONE
        # plane for the whole platform — the scorer built below stages
        # through it (measured H2D), the exporter refreshes its per-device
        # memory gauges on every scrape, and the SLO engine's budget
        # ledger (7c) reads its transfer digest in place of the h2d
        # reservation. CCFD_DEVICE=0 (or CR device.enabled: false) kills
        # the plane; everything downstream then keeps the pre-telemetry
        # fallbacks.
        dev_spec = spec.component("device")
        if dev_spec.enabled and cfg.device_enabled:
            from ccfd_tpu.observability.device import DeviceTelemetry

            self.device = DeviceTelemetry(registry=self._registry("device"))

        # 0f. decision provenance plane (observability/audit.py): ONE
        # AuditLog shared by every router worker — the route seam stamps
        # one DecisionRecord per routed transaction into a bounded ring
        # plus (with a dir) a segmented crash-safe log written through
        # the durability seam's framing. Built before the router so the
        # workers construct against it; the lifecycle (3b) wires the
        # per-batch lineage sample and the incident recorder (7d) the
        # open-incident join. CCFD_AUDIT=0 (or CR audit.enabled: false)
        # kills the plane: no records stamped, /decisions 404s.
        aud_spec = spec.component("audit")
        if aud_spec.enabled and cfg.audit_enabled:
            from ccfd_tpu.observability.audit import AuditLog
            from ccfd_tpu.runtime.supervisor import RestartPolicy

            self.audit = AuditLog(
                dir=(aud_spec.opt("dir", cfg.audit_dir) or None),
                max_records=int(aud_spec.opt("ring", cfg.audit_ring)),
                segment_bytes=int(
                    aud_spec.opt("segment_bytes", cfg.audit_segment_bytes)),
                retain_segments=int(
                    aud_spec.opt("segments", cfg.audit_segments)),
                registry=self._registry("audit"),
            )
            flush_s = float(
                aud_spec.opt("flush_interval_s", cfg.audit_flush_interval_s))
            self.supervisor.add_thread_service(
                "audit",
                lambda: self.audit.run(interval_s=flush_s),
                self.audit.stop,
                policy=RestartPolicy.ALWAYS,
                reset=self.audit.reset,
            )

        # 0e. multi-chip partitioning layer (parallel/partition.py): the
        # named (data, fsdp, tp) mesh + partitioner the serving/retrain
        # components below build AGAINST — constructed first so the scorer
        # (step 3) shards its params/batches from birth and the trainer
        # (step 7) jits its donated sharded step through the same layout.
        # Armed only when the resolved device count is > 1; a 1-device
        # platform keeps the historical unsharded path byte-for-byte.
        if spec.component("mesh").enabled:
            self._up_mesh(spec.component("mesh"))

        # 1. store (Ceph/S3, README.md:136-269) — serves the dataset
        if spec.component("store").enabled:
            self._up_store()

        # 2. bus (Kafka, README.md:87-134). With a `bus.url` (or a
        # non-inproc BROKER_URL) the platform is a CLIENT of a shared
        # networked bus — the fleet shape: N operator processes over ONE
        # broker, partition ownership via the bus's consumer groups.
        # Without one, the historical in-process Broker.
        if spec.component("bus").enabled:
            bus_spec = spec.component("bus")
            bus_url = bus_spec.opt("url", "") or (
                "" if cfg.broker_url.startswith("inproc")
                else cfg.broker_url)
            if bus_url:
                from ccfd_tpu.bus.client import broker_from_url

                self._broker_is_client = True
                self.broker = broker_from_url(
                    bus_url, registry=self._registry("bus"))
                if self.broker is None:
                    raise ValueError(
                        f"bus.url {bus_url!r}: expected http:// (networked "
                        "bus server) or kafka:// (real cluster)")
            else:
                from ccfd_tpu.bus.broker import Broker

                log_dir = bus_spec.opt("log_dir", "") or None
                self.broker = Broker(
                    default_partitions=int(bus_spec.opt("partitions", 3)),
                    log_dir=log_dir,
                    fsync=bool(bus_spec.opt("fsync", False)),
                )
        else:
            needs_bus = [
                n for n in ("engine", "notify", "router", "retrain",
                            "analytics", "producer")
                if spec.component(n).enabled
            ]
            if needs_bus:
                raise ValueError(
                    f"bus disabled in CR but required by: {needs_bus}"
                )

        # 3. model serving (Seldon, README.md:271-301)
        if spec.component("scorer").enabled:
            self._up_scorer()

        # 3b. model lifecycle (lifecycle/): governs how retrain candidates
        #     reach the scorer — shadow -> canary -> gated promotion with
        #     auto-rollback. Built BEFORE the router so the router's score
        #     lane can be wrapped with the shadow tap + canary gate, and
        #     before retrain so the trainer hands candidates to it. Needs
        #     a local scorer with a host forward (the challenger slot
        #     scores off-device by design) and the bus (shadow pairs +
        #     label joins ride topics).
        if (spec.component("lifecycle").enabled
                and self.scorer is not None and self.broker is not None):
            self._up_lifecycle()

        # 4. process engine (KIE, README.md:345-408)
        if spec.component("engine").enabled:
            self._up_engine()

        # 5. notification service (README.md:410-422)
        if spec.component("notify").enabled:
            self._up_notify()

        # 6. router (README.md:424-459)
        if spec.component("router").enabled:
            self._up_router()

        # 6b. engine crash recovery (engine opt `crash_recovery`): aligned
        #     checkpoints + bus-offset-rewind restore, the stronger story
        #     than the file-based `state_file` persistence — crash-
        #     consistent with the bus, and chaos-killable as a supervised
        #     service (runtime/recovery.py; drilled by tools/chaos_soak.py)
        if (spec.component("engine").enabled
                and spec.component("engine").opt("crash_recovery", False)
                and self.engine is not None and self.router is not None):
            self._up_crash_recovery()

        # 6c. investigator simulation (the demo's Business Central humans,
        #     reference README.md:547-581) — drains the task queue and
        #     feeds the user-task model its training labels
        if (spec.component("investigator").enabled
                and self.engine is not None):
            self._up_investigator()

        # 7. online retrain (new capability; BASELINE.json configs[4]) —
        #    the trainer's step is the MLP's; a history-aware seq scorer
        #    cannot consume it (and a hot-swap would publish MLP params
        #    into the seq jit), so retrain is skipped for model=seq
        if spec.component("retrain").enabled and self.scorer is not None:
            from ccfd_tpu.serving.history import SeqScorer

            if isinstance(self.scorer, SeqScorer):
                logging.getLogger(__name__).warning(
                    "retrain enabled but scorer model is 'seq': online "
                    "retrain targets the MLP family; skipping retrain"
                )
            else:
                self._up_retrain()

        # 7b. analytics / drift monitor (notebooks+spark analog,
        #     reference frauddetection_cr.yaml:7-53)
        if spec.component("analytics").enabled:
            self._up_analytics()

        # 7c. SLO engine (observability/slo.py): built once the components
        #     whose histograms/counters it reads exist. Declarative specs
        #     from the CR `slo:` block (or the CCFD_SLO_* defaults:
        #     e2e-p99 / rest-p99 / error-rate), multi-window burn-rate
        #     gauges + breach alerts, and the REST-path budget ledger over
        #     the stage profiler. Runs as a supervised service.
        if self.profiler is not None:
            from ccfd_tpu.observability.slo import SLOEngine
            from ccfd_tpu.runtime.supervisor import RestartPolicy

            self.slo = SLOEngine.from_config(
                cfg, self.registries, self._registry("slo"),
                profiler=self.profiler, options=slo_spec.options,
                telemetry=self.device,
            )
            interval = float(slo_spec.opt("interval_s", cfg.slo_interval_s))
            self.supervisor.add_thread_service(
                "slo",
                lambda: self.slo.run(interval_s=interval),
                self.slo.stop,
                policy=RestartPolicy.ALWAYS,
                reset=self.slo.reset,
            )

        # 7c2. capacity observatory (observability/capacity.py): the
        #      queueing model fitted over the live stage profile —
        #      predicted p50/p99 per stage and end-to-end, bottleneck
        #      attribution + headroom, what-if evaluation over the PR 6
        #      actuator vocabulary, and a service-curve regression
        #      sentinel persisting its baseline through the durability
        #      seam. Served at /capacity (+ /capacity/whatif) below.
        #      CCFD_CAPACITY=0 (or CR capacity.enabled: false) kills it.
        cap_spec = spec.component("capacity")
        if (cap_spec.enabled and cfg.capacity_enabled
                and self.profiler is not None):
            from ccfd_tpu.observability.capacity import CapacityModel
            from ccfd_tpu.runtime.supervisor import RestartPolicy

            self.capacity = CapacityModel(
                self.profiler,
                registry=self._registry("capacity"),
                baseline_path=(
                    cap_spec.opt("baseline_file", cfg.capacity_baseline_file)
                    or None),
                regression_tolerance=float(
                    cap_spec.opt("regression_tolerance",
                                 cfg.capacity_regression_tolerance)),
                min_samples=int(
                    cap_spec.opt("min_samples", cfg.capacity_min_samples)),
            )
            # seed the what-if evaluator with the live actuator values so
            # "what if workers=N" is a delta against what actually runs
            workers = int(self.spec.component("router")
                          .opt("workers", cfg.router_workers))
            self.capacity.set_actuators(
                workers=max(1, workers),
                batch=(max(cfg.batch_sizes) if cfg.batch_sizes else None),
                deadline_ms=cfg.batch_deadline_ms,
                max_inflight=(int(self._overload.budget.limit)
                              if self._overload is not None else None),
            )
            cap_interval = float(
                cap_spec.opt("interval_s", cfg.capacity_interval_s))
            self.supervisor.add_thread_service(
                "capacity",
                lambda: self.capacity.run(interval_s=cap_interval),
                self.capacity.stop,
                policy=RestartPolicy.ALWAYS,
                reset=self.capacity.reset,
            )

        # 7d. incident flight recorder (observability/incident.py): the
        #     bounded snapshot ring runs as a supervised service; the SLO
        #     engine's breach edge dumps a schema-validated bundle, and a
        #     dispatch-watchdog kill snapshots into the ring. Served at
        #     the exporter's /incidents endpoints below. CCFD_INCIDENT=0
        #     (or CR incident.enabled: false) kills the plane.
        inc_spec = spec.component("incident")
        if inc_spec.enabled and cfg.incident_enabled:
            from ccfd_tpu.observability.incident import FlightRecorder
            from ccfd_tpu.runtime.supervisor import RestartPolicy

            self.recorder = FlightRecorder(
                self.registries,
                registry=self._registry("incident"),
                profiler=self.profiler,
                telemetry=self.device,
                sink=self.trace_sink,
                ring=int(inc_spec.opt("ring", cfg.incident_ring)),
                out_dir=(inc_spec.opt("dir", cfg.incident_dir) or None),
                max_bundles=int(inc_spec.opt("max_bundles", 16)),
                timeout_debounce_s=float(
                    inc_spec.opt("timeout_debounce_s", 2.0)),
                audit=self.audit,  # bundles embed in-flight decisions
                capacity=self.capacity,  # + capacity snapshot at breach
            )
            if self.slo is not None:
                self.slo.add_breach_listener(self.recorder.on_breach)
            if self.audit is not None:
                # open-incident join for the decision records: while any
                # SLO is in the breaching state, routed transactions are
                # stamped with the newest bundle's id — "this score was
                # made DURING inc-0007" is a query, not a log dig. With
                # no burn-rate state (CCFD_SLO=0) there is no notion of
                # "still open", so nothing links (documented).
                rec, eng = self.recorder, self.slo

                def _open_incident():
                    if eng is None or not eng.any_breaching():
                        return None
                    return rec.last_incident_id()

                self.audit.incident_fn = _open_incident
            if self._overload is not None:
                self._overload.recorder = self.recorder
            if self.storage_gate is not None:
                # storage quarantines dump a post-mortem bundle too
                from ccfd_tpu.runtime import durability

                durability.set_recorder(self.recorder.incident)
            inc_interval = float(
                inc_spec.opt("interval_s", cfg.incident_interval_s))
            self.supervisor.add_thread_service(
                "incident",
                lambda: self.recorder.run(interval_s=inc_interval),
                self.recorder.stop,
                policy=RestartPolicy.ALWAYS,
                reset=self.recorder.reset,
            )

        # 7e. device heal supervisor (runtime/heal.py): the health state
        #     machine over the local scorer — canary dispatches bounded by
        #     the router's PR 6 watchdog, quarantine pins the router's
        #     degradation ladder to the host tier, the heal ladder's
        #     respawn rung restores the lifecycle champion checkpoint, and
        #     re-promotion is warm (full executable inventory precompiled
        #     under the heal.warm label). Default on with a local scorer;
        #     CCFD_HEAL=0 (or CR heal.enabled: false) kills the plane.
        heal_spec = spec.component("heal")
        if (heal_spec.enabled and cfg.heal_enabled
                and self.scorer is not None):
            self._up_heal(heal_spec)

        # 8. monitoring (README.md:487-537)
        if spec.component("monitoring").enabled:
            from ccfd_tpu.metrics.exporter import MetricsExporter

            mon = spec.component("monitoring")
            self.exporter = MetricsExporter(
                self.registries,
                host=mon.opt("host", "127.0.0.1"),
                port=int(mon.opt("port", 0)),
                sink=self.trace_sink,  # /traces + /traces/<id> endpoints
                profiler=self.profiler,  # /profile StageProfile endpoint
                telemetry=self.device,  # device gauges + /debug endpoints
                recorder=self.recorder,  # /incidents + /incidents/<id>
                audit=self.audit,  # /decisions + /decisions/<tx_id>
                capacity=self.capacity,  # /capacity + /capacity/whatif
                health=self._health_verdict,  # /healthz readiness rollup
            ).start()
            self._wire_memory_probes()

        if spec.component("health").enabled:
            from ccfd_tpu.runtime.health import HealthServer

            h = spec.component("health")
            self.health_server = HealthServer(
                self.supervisor,
                host=h.opt("host", "127.0.0.1"),
                port=int(h.opt("port", 0)),
            ).start()

        # 8b. fleet member plane (fleet/member.py): heartbeat endpoint +
        #     gossip loop + fleet actuators (admission rescale, parity
        #     quarantine, aggregator duty). Built after everything it
        #     observes (router, overload, scorer, recorder) and before
        #     the supervisor starts so the gossip loop runs supervised.
        fl_spec = spec.component("fleet")
        if fl_spec.enabled and self.broker is not None:
            self._up_fleet(fl_spec)

        self.supervisor.start()
        if not self.supervisor.wait_ready(timeout_s=wait_ready_s):
            raise TimeoutError(
                f"platform not ready after {wait_ready_s}s: "
                f"{self.supervisor.status()}"
            )

        # 9. producer last (README.md:461-485) — starts the traffic
        if spec.component("producer").enabled:
            self._up_producer()

        # 10. chaos (opt-in; no reference analog): seeded fault injection
        # over the supervised services, so recovery machinery is exercised
        # continuously instead of trusted
        if spec.component("chaos").enabled:
            from ccfd_tpu.runtime.chaos import ChaosMonkey

            c = spec.component("chaos")
            targets = c.opt("targets", None)
            self.chaos = ChaosMonkey(
                self.supervisor,
                interval_s=float(c.opt("interval_s", 30.0)),
                seed=int(c.opt("seed", 0)),
                # targets: [] is a valid choice — storms only, no kills
                targets=(list(targets) if targets is not None else None),
                registry=self._registry("chaos"),
                fault_plan=self.fault_plan,
                device_fault_plan=(self.device_fault_plan
                                   if self._device_storm_driven else None),
                storage_fault_plan=(self.storage_fault_plan
                                    if self._storage_storm_driven else None),
                fault_interval_s=(float(c.opt("fault_interval_s"))
                                  if c.opt("fault_interval_s") else None),
                fault_duration_s=float(c.opt("fault_duration_s", 2.0)),
            ).start()

        self._up = True
        return self

    # -- per-component builders -------------------------------------------
    def _registry(self, name: str):
        from ccfd_tpu.metrics.prom import Registry

        if name not in self.registries:
            self.registries[name] = Registry()
            if self.exporter is not None:  # registries created post-start
                self.exporter.add(name, self.registries[name])
        return self.registries[name]

    def _tracer(self, component: str):
        """Component tracer wired to the component's SCRAPED registry and
        the shared tail-sampling sink; None with tracing disabled (every
        consumer treats a None tracer as 'tracing off')."""
        if self.trace_sink is None:
            return None
        from ccfd_tpu.observability.trace import Tracer

        return Tracer(self._registry(component), component=component,
                      sink=self.trace_sink)

    def _up_store(self) -> None:
        from ccfd_tpu.data.ccfd import load_dataset, to_csv_bytes
        from ccfd_tpu.store.objectstore import Credentials, ObjectStore
        from ccfd_tpu.store.server import StoreServer

        c = self.spec.component("store")
        cfg = self.cfg
        store = ObjectStore(root=c.opt("root"))
        store.add_credentials(
            Credentials(
                cfg.access_key_id or "ccfd-access",
                cfg.secret_access_key or "ccfd-secret",
            )
        )
        store.create_bucket(cfg.s3_bucket)
        if c.opt("seed_dataset", True):
            try:
                store.get(cfg.s3_bucket, cfg.filename)
            except Exception:  # noqa: BLE001 — absent: upload (README.md:303-343)
                store.put(cfg.s3_bucket, cfg.filename, to_csv_bytes(load_dataset()))
        self.store_server = StoreServer(
            store, host=c.opt("host", "127.0.0.1"), port=int(c.opt("port", 0))
        ).start()
        # repoint the producer's endpoint at the live store
        self.cfg = dataclasses.replace(
            self.cfg,
            s3_endpoint=self.store_server.endpoint,
            access_key_id=self.cfg.access_key_id or "ccfd-access",
            secret_access_key=self.cfg.secret_access_key or "ccfd-secret",
        )

    def _up_mesh(self, c: ComponentSpec) -> None:
        """Build the serving mesh + partitioner (parallel/partition.py).

        CR ``mesh:`` block over the ``CCFD_MESH_*`` env twins: ``devices``
        (1 = single-device, 0 = every local device, N = the first N),
        ``fsdp``/``tp`` axis sizes (data absorbs the remainder),
        ``param_partition`` (replicated | rules) and ``seq_parallel``
        (none | ring | ulysses — the seq family's L-sharded attention).
        """
        import jax

        cfg = self.cfg
        log_ = logging.getLogger(__name__)
        n = int(c.opt("devices", cfg.mesh_devices))
        avail = len(jax.devices())
        if n == 0:
            n = avail
        fsdp = max(1, int(c.opt("fsdp", cfg.mesh_fsdp)))
        tp = max(1, int(c.opt("tp", cfg.mesh_tp)))
        self._mesh_seq_parallel = str(
            c.opt("seq_parallel", cfg.mesh_seq_parallel) or "none")
        if n > avail:
            # a CR sized for an 8-chip pod brought up on a laptop must
            # still serve — clamp, but LOUDLY: the operator asked for
            # hardware that is not there. The clamped count may break the
            # CR's fsdp/tp factorization and a 1-device clamp cannot
            # carry seq_parallel at all, so the whole shape degrades to
            # what the clamped hardware CAN serve (pure data parallel)
            # rather than crashing scorer construction.
            logging.getLogger(__name__).warning(
                "mesh.devices=%d but only %d local devices; clamping "
                "(set XLA_FLAGS=--xla_force_host_platform_device_count "
                "for a virtual CPU mesh)", n, avail)
            n = avail
            if n % (fsdp * tp) != 0:
                log_.warning(
                    "clamped mesh: %d devices not divisible by "
                    "fsdp*tp=%d; serving pure data-parallel instead",
                    n, fsdp * tp)
                fsdp = tp = 1
        if tp <= 1 and self._mesh_seq_parallel != "none":
            if n > 1:
                log_.warning(
                    "mesh.seq_parallel=%s needs a tp axis > 1 (have "
                    "tp=%d); disabling sequence parallelism",
                    self._mesh_seq_parallel, tp)
            self._mesh_seq_parallel = "none"
        if n <= 1:
            self._mesh_seq_parallel = "none"
            return
        from ccfd_tpu.parallel.mesh import make_named_mesh
        from ccfd_tpu.parallel.partition import partitioner_from_config

        model = self.spec.component("scorer").opt("model", cfg.model_name)
        self.mesh = make_named_mesh(jax.devices()[:n], fsdp=fsdp, tp=tp)
        self._mesh_param_partition = str(
            c.opt("param_partition", cfg.mesh_param_partition))
        self.partitioner = partitioner_from_config(
            self.mesh, self._mesh_param_partition, model=str(model),
        )
        reg = self._registry("mesh")
        reg.gauge(
            "ccfd_mesh_devices",
            "devices in the live serving mesh (absent/0 = unsharded)",
        ).set(float(n))
        g_axis = reg.gauge(
            "ccfd_mesh_axis_size", "named serving-mesh axis sizes")
        for axis, size in self.mesh.shape.items():
            g_axis.set(float(size), labels={"axis": str(axis)})

    def _up_scorer(self) -> None:
        from ccfd_tpu.serving.scorer import Scorer

        c = self.spec.component("scorer")
        cfg = self.cfg
        if c.opt("model", cfg.model_name) in ("seq", "seq_q8"):
            # history-aware long-context family (serving/history.py):
            # streamed through the router (history lives where the stream
            # is); the stateless REST front stays row-based by design
            import jax

            from ccfd_tpu.data.ccfd import synthetic_dataset
            from ccfd_tpu.models import seq as seq_mod
            from ccfd_tpu.serving.history import SeqScorer

            sparams = seq_mod.init(jax.random.PRNGKey(0))
            ds = synthetic_dataset(n=4096, fraud_rate=0.01, seed=0)
            sparams = seq_mod.set_normalizer(
                sparams, ds.X.mean(0), ds.X.std(0)
            )
            if c.opt("model", cfg.model_name) == "seq_q8":
                # int8 serving variant (ops/seq_quant.py) straight from
                # the CR — the governed route is still the lifecycle
                # shadow lane; this is the explicit operator choice
                from ccfd_tpu.ops.seq_quant import quantize_seq

                sparams = quantize_seq(sparams)
            self.scorer = SeqScorer(
                sparams,
                length=int(c.opt("history_length", 64)),
                batch_sizes=cfg.batch_sizes,
                compute_dtype=c.opt("dtype", cfg.compute_dtype),
                max_customers=int(c.opt("max_customers", 20_000)),
                registry=self._registry("seldon"),
                stripes=int(c.opt("seq_stripes", cfg.seq_stripes)),
                inflight=int(c.opt("seq_inflight", cfg.seq_inflight)),
                len_buckets=tuple(
                    c.opt("seq_len_buckets", cfg.seq_len_buckets)),
                telemetry=self.device,
                partitioner=self.partitioner,
                seq_parallel=getattr(self, "_mesh_seq_parallel", "none"),
            )
            self.scorer.warmup()
            if self.device is not None:
                self.device.register_executable_source(
                    "seq", self.scorer.executable_grid)
            return
        params = None
        if c.opt("train_steps", 0):
            from ccfd_tpu.data.ccfd import load_dataset
            from ccfd_tpu.parallel.train import TrainConfig, fit_mlp

            ds = load_dataset()
            params = fit_mlp(
                ds.X, ds.y, steps=int(c.opt("train_steps")),
                tc=TrainConfig(compute_dtype="float32"),
            )
        self.scorer = Scorer(
            model_name=c.opt("model", cfg.model_name),
            params=params,
            compute_dtype=c.opt("dtype", cfg.compute_dtype),
            batch_sizes=cfg.batch_sizes,
            host_tier_rows=None if cfg.host_tier_rows < 0 else cfg.host_tier_rows,
            dispatch_deadline_ms=cfg.scorer_dispatch_deadline_ms(),
            telemetry=self.device,
            partitioner=self.partitioner,
        )
        self.scorer.warmup()
        if self.device is not None:
            self.device.register_executable_source(
                "scorer", self.scorer.executable_grid)
        if c.opt("rest", False):
            from ccfd_tpu.serving.server import PredictionServer

            self.prediction_server = PredictionServer(
                self.scorer, self.cfg, self._registry("seldon"),
                tracer=self._tracer("seldon"),
                profiler=self.profiler,
            )
            self.prediction_host = c.opt("host", "127.0.0.1")
            self.prediction_port = self.prediction_server.start(
                self.prediction_host, int(c.opt("port", 0))
            )

    def _up_lifecycle(self) -> None:
        from ccfd_tpu.runtime.supervisor import RestartPolicy
        from ccfd_tpu.serving.history import SeqScorer

        is_seq = isinstance(self.scorer, SeqScorer)
        if not is_seq and not getattr(self.scorer, "has_host_forward", False):
            logging.getLogger(__name__).warning(
                "lifecycle enabled but the scorer has no host forward "
                "(model=%s): the challenger slot scores off-device by "
                "design; skipping lifecycle",
                getattr(getattr(self.scorer, "spec", None), "name", "?"),
            )
            return
        from ccfd_tpu.lifecycle.controller import (
            Guardrails,
            LifecycleController,
        )
        from ccfd_tpu.lifecycle.evaluator import ShadowEvaluator
        from ccfd_tpu.lifecycle.shadow import ShadowTap
        from ccfd_tpu.lifecycle.versions import VersionStore
        from ccfd_tpu.parallel.checkpoint import CheckpointManager

        c = self.spec.component("lifecycle")
        cfg = self.cfg
        registry = self._registry("lifecycle")
        state_dir = c.opt("state_dir", cfg.lifecycle_dir) or ""
        store = VersionStore(
            os.path.join(state_dir, "versions.json") if state_dir else None
        )
        if state_dir:
            ckpt_dir = os.path.join(state_dir, "checkpoints")
        else:
            # in-memory lineage still needs somewhere for rollback
            # checkpoints to live for the process lifetime
            import tempfile

            ckpt_dir = tempfile.mkdtemp(prefix="ccfd_lifecycle_")
        checkpoints = CheckpointManager(
            ckpt_dir, keep=int(c.opt("keep_checkpoints", 8))
        )
        shadow = ShadowTap(
            self.scorer, self.broker, cfg.shadow_topic, registry,
            max_queued_batches=int(c.opt("shadow_queue_batches", 64)),
        )
        evaluator = ShadowEvaluator(
            cfg, self.broker, self.scorer, registry,
            k_frac=float(c.opt("precision_k_frac", 0.05)),
        )
        guardrails = Guardrails(
            min_labels=int(c.opt("min_labels", cfg.lifecycle_min_labels)),
            min_shadow_rows=int(
                c.opt("min_shadow_rows", cfg.lifecycle_min_shadow_rows)),
            auc_margin=float(c.opt("auc_margin", cfg.lifecycle_auc_margin)),
            max_alert_rate_delta=float(
                c.opt("max_alert_rate_delta", cfg.lifecycle_max_alert_delta)),
            max_score_psi=float(
                c.opt("max_score_psi", cfg.lifecycle_max_psi)),
            canary_weight=float(
                c.opt("canary_weight", cfg.lifecycle_canary_weight)),
            canary_min_labels=int(
                c.opt("canary_min_labels", cfg.lifecycle_canary_min_labels)),
            min_submit_interval_s=float(
                c.opt("min_submit_interval_s",
                      cfg.lifecycle_min_submit_interval_s)),
        )
        self.lifecycle = LifecycleController(
            cfg, self.scorer, store=store, checkpoints=checkpoints,
            shadow=shadow, evaluator=evaluator, guardrails=guardrails,
            registry=registry,
            # storage-integrity pin (runtime/durability.py): when no
            # champion checkpoint generation verifies at restore, serving
            # pins to the rules tier through the heal-gate seam instead
            # of publishing an unverified tree
            storage_pin=(self.storage_gate.pin
                         if self.storage_gate is not None else None),
            storage_unpin=(self.storage_gate.unpin
                           if self.storage_gate is not None else None),
        )
        if is_seq:
            # the router calls a SeqScorer as an OBJECT (score_with_ids),
            # so there is no score_fn lane to wrap — the scorer offers
            # each resolved batch to the tap itself (challenger slot —
            # typically the int8 seq_q8 variant — scores tapped histories
            # on the tap's worker thread, sample-bounded) and serves the
            # canary gate's deterministic challenger slice against the
            # same assembled contexts
            self.scorer.shadow_tap = shadow
            self.scorer.canary_gate = self.lifecycle.gate
            if len(self.scorer.len_buckets) > 1:
                # ladder + lifecycle: tapped champion scores come from
                # short-rung executables while the challenger re-scores
                # the full-L contexts, so the PSI/alert evidence absorbs
                # rung noise on cold rows (conservative bias — breaches
                # read larger, never smaller). Judge candidates with the
                # ladder off for a clean variant-only verdict.
                logging.getLogger(__name__).warning(
                    "lifecycle shadow evaluation with seq len_buckets=%s "
                    "armed: champion scores ride short-L rungs while the "
                    "challenger scores full-L contexts — distribution "
                    "gates will include ladder-rung noise (conservative)",
                    self.scorer.len_buckets,
                )
        if self.audit is not None:
            # per-batch lineage sample for the decision records: the route
            # seam joins each batch to the serving champion's version id +
            # checkpoint hash — sampled once per batch, never per row
            def _lineage_sample(store=store):
                v = store.champion()
                return ((v.version, v.checkpoint_hash)
                        if v is not None else (None, None))

            self.audit.lineage_fn = _lineage_sample
        interval = float(c.opt("interval_s", 0.25))
        self.supervisor.add_thread_service(
            "lifecycle",
            lambda: self.lifecycle.run(interval_s=interval),
            self.lifecycle.stop,
            policy=RestartPolicy.ALWAYS,
            reset=self.lifecycle.reset,
        )
        self.supervisor.add_thread_service(
            "lifecycle-shadow",
            lambda: shadow.run(interval_s=0.05),
            shadow.stop,
            policy=RestartPolicy.ALWAYS,
            reset=shadow.reset,
        )

    def _up_engine(self) -> None:
        from ccfd_tpu.process.fraud import build_engine
        from ccfd_tpu.process.prediction import ScorerPredictionService

        c = self.spec.component("engine")
        listener = None
        if c.opt("usertask_model", False):
            # dedicated learned user-task model (the reference's second
            # Seldon model, README.md:347-353): trains on investigator
            # decisions, replaces the fraud-scorer-backed service
            from ccfd_tpu.process.usertask_model import OnlineUserTaskModel

            self.usertask_model = OnlineUserTaskModel(
                min_examples=int(c.opt("usertask_min_examples", 32)),
            )
            self._usertask_state_file = c.opt("usertask_state_file", "") or None
            if self._usertask_state_file and os.path.exists(self._usertask_state_file):
                try:
                    self.usertask_model.load(self._usertask_state_file)
                except Exception:  # noqa: BLE001 - an unrecoverable state
                    # file (quarantined by the durability layer, no
                    # verifiable generation) must read as a cold model,
                    # never brick bring-up
                    logging.getLogger(__name__).exception(
                        "usertask state %s unusable; starting cold",
                        self._usertask_state_file)
            pred = self.usertask_model
            listener = self.usertask_model.observe
        else:
            pred = (
                ScorerPredictionService(self.scorer.score)
                if self.scorer is not None
                else None
            )
        def engine_factory():
            # crash recovery rebuilds with the same wiring (definitions are
            # code; the shared registry keeps counters cumulative across
            # engine epochs)
            return build_engine(
                self.cfg, self.broker, self._registry("kie"),
                prediction_service=pred, task_listener=listener,
            )

        self._engine_factory = engine_factory
        self.engine = engine_factory()
        # jBPM-style engine persistence: restore process state across
        # restarts (overdue timers fire promptly after restore)
        state_file = c.opt("state_file", "")
        self._engine_state_file = state_file or None
        if state_file and os.path.exists(state_file):
            try:
                self.engine.load(state_file)
            except Exception:  # noqa: BLE001 - corrupt beyond every
                # retained generation: cold engine beats a bricked boot
                logging.getLogger(__name__).exception(
                    "engine state %s unusable; starting cold", state_file)
        if state_file or getattr(self, "_usertask_state_file", None):
            # periodic checkpoint: a crash between saves loses at most
            # save_interval_s of process state — save-on-down alone would
            # lose everything exactly when persistence matters (SIGKILL/OOM)
            from ccfd_tpu.runtime.supervisor import RestartPolicy

            interval = float(c.opt("save_interval_s", 5.0))
            stop = threading.Event()

            def checkpoint_loop() -> None:
                while not stop.wait(interval):
                    self._save_engine_state()

            self.supervisor.add_thread_service(
                "engine-persist", checkpoint_loop, stop.set,
                policy=RestartPolicy.ALWAYS, reset=stop.clear,
            )
        if c.opt("rest", False):
            # KIE-shaped REST surface (reference :8090, README.md:509-515).
            # Started strictly AFTER the snapshot restore: an early remote
            # start_process would populate the engine and make restore()
            # refuse ("requires an empty engine").
            from ccfd_tpu.process.server import EngineServer

            self.engine_server = EngineServer(
                self.engine, tracer=self._tracer("kie"))
            self.engine_port = self.engine_server.start(
                c.opt("rest_host", "127.0.0.1"), int(c.opt("rest_port", 0))
            )

    def _up_notify(self) -> None:
        from ccfd_tpu.notify.service import NotificationService
        from ccfd_tpu.runtime.supervisor import RestartPolicy

        c = self.spec.component("notify")
        notify = NotificationService(
            self.cfg, self.broker, self._registry("notify"),
            seed=int(c.opt("seed", 0)),
            tracer=self._tracer("notify"),
        )
        self.supervisor.add_thread_service(
            "notify",
            lambda: notify.run(poll_timeout_s=0.02),
            notify.stop,
            policy=RestartPolicy.ALWAYS,
            reset=notify.reset,
        )

    def _up_router(self) -> None:
        from ccfd_tpu.router.router import Router
        from ccfd_tpu.runtime.supervisor import RestartPolicy

        c = self.spec.component("router")
        reg = self._registry("router")
        router_tracer = self._tracer("router")
        host_score_fn = None
        if self.scorer is not None:
            from ccfd_tpu.serving.history import SeqScorer

            # a history-aware scorer goes in as the OBJECT so the router
            # detects score_with_ids and feeds it the decoded records
            score_fn = (self.scorer if isinstance(self.scorer, SeqScorer)
                        else self.scorer.score)
            if getattr(self.scorer, "has_host_forward", False):
                # the ladder's host tier: a numpy forward that never
                # touches the (possibly partitioned) device edge
                host_score_fn = self.scorer.host_score
        else:  # remote scorer over the Seldon REST contract
            from ccfd_tpu.serving.client import SeldonClient

            score_fn = SeldonClient(
                self.cfg,
                faults=(self.fault_plan.injector("scorer", reg)
                        if self.fault_plan else None),
                tracer=router_tracer,
            ).score
        if self.fault_plan is not None and self.scorer is not None:
            # in-process scorer edge: same injection point the REST client
            # gets, wrapped around the callable
            inj = self.fault_plan.injector("scorer", reg)
            if inj is not None:
                if hasattr(score_fn, "score_with_ids"):
                    score_fn = inj.wrap(score_fn)  # SeqScorer object
                else:
                    score_fn = inj.wrap_fn(score_fn)
        breaker = None
        if self.lifecycle is not None and not hasattr(
                score_fn, "score_with_ids"):
            # lifecycle serving lane: shadow tap inside (pure champion
            # pairs), canary gate outside (challenger-arm override). Sits
            # UNDER the ParallelRouter's coalescing batcher, so the tap
            # observes the same coalesced batches the device scores.
            # Faults injected above stay inside the wrap: a fault-storm
            # failure degrades the ladder, not the lifecycle accounting.
            score_fn = self.lifecycle.wrap_score(score_fn)
            # one scorer-edge breaker, shared between the router's
            # degradation ladder and the controller's canary guardrail
            # (a breaker leaving CLOSED mid-canary is a rollback trigger)
            if bool(c.opt("degrade", True)):
                from ccfd_tpu.router.router import default_scorer_breaker

                breaker = default_scorer_breaker(reg)
                self.lifecycle.breaker = breaker
        engine = self.engine
        if engine is None and self.cfg.kie_server_url.startswith("http"):
            # remote engine over the KIE-shaped REST contract
            from ccfd_tpu.process.client import EngineRestClient

            engine = EngineRestClient(
                self.cfg.kie_server_url,
                timeout_s=self.cfg.seldon_timeout_ms / 1000.0,
                retries=self.cfg.client_retries,
                tracer=router_tracer,
            )
        if self.fault_plan is not None and engine is not None:
            inj = self.fault_plan.injector("engine", reg)
            if inj is not None:
                engine = inj.wrap(
                    engine,
                    methods=("start_process", "start_process_batch",
                             "signal"),
                )
        # overload-control plane (runtime/overload.py): default on — the
        # static in-flight cap becomes an adaptive AIMD limit derived
        # from the scorer stage's observed latency, sheds become
        # priority-aware, and a hung dispatch is watchdog-killed into the
        # breaker. One OverloadControl per router pool: with workers > 1
        # every worker shares it, so the adaptive bound is global.
        workers = int(c.opt("workers", self.cfg.router_workers))
        overload = None
        if self.cfg.overload_enabled:
            from ccfd_tpu.runtime.overload import OverloadControl

            n_eff = workers if workers > 0 else max(
                1, len(self.broker.end_offsets(self.cfg.kafka_topic)))
            overload = OverloadControl.from_config(
                self.cfg, reg, max_batch=4096, workers=n_eff)
            mi = c.opt("max_inflight")
            if overload is not None and mi is not None:
                # an explicit CR cap stays a hard ceiling on the
                # adaptive limit — AIMD moves below it, never above.
                # min_limit clamps too: a floor above the cap would let
                # the first AIMD decrease snap the limit back OVER the
                # operator's bound (max(min_limit, limit*beta))
                b = overload.budget
                b.max_limit = min(b.max_limit, int(mi))
                b.min_limit = min(b.min_limit, int(mi))
                b.limit = min(b.limit, int(mi))
        # kept for the incident recorder (7d): a dispatch-watchdog kill
        # snapshots into the flight recorder's ring
        self._overload = overload
        # fleet mode (fleet/): the audit seam is wrapped with the ledger
        # tap (per-tx dispositions onto the shared bus, stamped with the
        # poll epoch) and offsets move to commit-after-route — a member
        # SIGKILLed mid-batch leaves the batch uncommitted for a survivor
        # to redeliver, and its own late commit is fenced by the bus
        fleet_spec = self.spec.component("fleet")
        audit_sink = self.audit
        commit_after_route = False
        if fleet_spec.enabled and self.broker is not None:
            from ccfd_tpu.fleet.ledger import FleetLedgerTap

            member_name = str(
                fleet_spec.opt("member", self.cfg.fleet_member)
                or f"member-{os.getpid()}")
            self.fleet_ledger = FleetLedgerTap(
                self.broker,
                member_name,
                topic=str(fleet_spec.opt("ledger_topic",
                                         self.cfg.fleet_ledger_topic)),
                inner=self.audit,
                registry=self._registry("fleet"),
            )
            audit_sink = self.fleet_ledger
            commit_after_route = True
        # replay plane (replay/): the verdict tap wraps the (possibly
        # fleet-wrapped) audit seam — live decisions pass through to the
        # provenance log; replay-marked ones divert to the parity join.
        # The tap also answers capture_rows for the route seam, arming
        # feature-row embeds so recorded windows are re-scorable.
        replay_spec = self.spec.component("replay")
        if ((replay_spec.enabled or self.cfg.replay_enabled)
                and self.audit is not None and self.broker is not None):
            from ccfd_tpu.replay.service import ReplayVerdictTap

            self.replay_tap = ReplayVerdictTap(
                inner=audit_sink, registry=self._registry("replay"))
            audit_sink = self.replay_tap
        # fused decision plane (ops/fused_decision.py, serving/fused.py):
        # CR `scorer.fused_decision` over CCFD_FUSED_DECISION. One device
        # dispatch returns (proba, fired rule index) — score, threshold
        # and the vectorizable rule base in ONE executable — and the
        # router's host rules pass disappears on the healthy path. Armed
        # only for an in-process row Scorer: seq/remote scorers have no
        # fusable decision program, and the lifecycle canary gate rewrites
        # scores AFTER the scorer returns — a fused verdict would have
        # fired on the pre-override score, splitting proba and rule.
        decision_fn = None
        rules = None
        sc_spec = self.spec.component("scorer")
        if bool(sc_spec.opt("fused_decision", self.cfg.fused_decision)):
            from ccfd_tpu.serving.history import SeqScorer

            fused_strict = bool(sc_spec.opt(
                "fused_decision_strict", self.cfg.fused_decision_strict))
            log_f = logging.getLogger(__name__)
            if self.scorer is None or isinstance(self.scorer, SeqScorer):
                msg = ("scorer.fused_decision needs an in-process row "
                       "Scorer (remote and seq scorers have no fusable "
                       "decision program); serving the staged path")
                if fused_strict:
                    raise RuntimeError(msg)
                log_f.warning(msg)
            elif self.lifecycle is not None:
                msg = ("scorer.fused_decision is incompatible with the "
                       "lifecycle serving lane (the canary gate overrides "
                       "scores after the fused verdict fires); serving "
                       "the staged path")
                if fused_strict:
                    raise RuntimeError(msg)
                log_f.warning(msg)
            else:
                from ccfd_tpu.router.rules import RuleSet, default_rules
                from ccfd_tpu.serving.fused import FusedDecisionScorer

                # the Router's own precedence (explicit arg > CCFD_RULES
                # file > threshold default), applied HERE so the fused
                # plan and the router provably share ONE RuleSet instance
                # (the router disarms on identity mismatch)
                rules = (RuleSet.from_file(self.cfg.rules_file)
                         if self.cfg.rules_file
                         else default_rules(self.cfg.fraud_threshold))
                fds = FusedDecisionScorer(
                    self.scorer, rules, registry=reg,
                    profiler=self.profiler, strict=fused_strict)
                if fds.enabled:
                    fds.warmup()  # every (L,B) bucket under fused.warm
                    if self.device is not None:
                        self.device.register_executable_source(
                            "fused_decision", fds.executable_grid)
                    # param swaps precompile the fused grid against the
                    # STAGED tree before publishing (scorer prepublish
                    # seam) — zero serving-stage compiles after a swap
                    self.scorer.add_prepublish_hook(fds.prepublish)
                    decision_fn = fds
                    self.fused_decision = fds
                else:  # refused (unvectorizable rules, mesh scorer):
                    rules = None  # the warning already said why; staged
        common = dict(
            rules=rules,
            decision_fn=decision_fn,
            host_score_fn=host_score_fn,
            breaker=breaker,
            # the ladder is the production default: a sick scorer edge
            # degrades scoring quality instead of dropping batches
            # (router.degrade: false restores the historical drop path)
            degrade=bool(c.opt("degrade", True)),
            max_inflight=(int(c.opt("max_inflight"))
                          if c.opt("max_inflight") is not None else None),
            tracer=router_tracer,
            overload=overload,
            profiler=self.profiler,
            audit=audit_sink,
            commit_after_route=commit_after_route,
        )
        # partition-parallel fan-out (router/parallel.py): CR
        # `router.workers` over CCFD_ROUTER_WORKERS; 1 = the historical
        # single Router, 0 = one worker per bus partition. Workers split
        # partitions via the consumer group and share one scorer through
        # a coalescing batcher, one in-flight budget, one breaker and a
        # group-wide pause barrier — the checkpoint/recovery machinery
        # below drives either shape through the same surface.
        if workers == 1:
            router = Router(self.cfg, self.broker, score_fn, engine, reg,
                            **common)
        else:
            from ccfd_tpu.router.parallel import ParallelRouter

            router = ParallelRouter(
                self.cfg, self.broker, score_fn, engine, reg,
                workers=workers,
                coalesce=bool(c.opt("coalesce", self.cfg.router_coalesce)),
                **common,
            )
        self.router = router
        if self.fleet_ledger is not None:
            # ledger entries stamp the tx consumer's poll epoch (members
            # run workers=1, so the consumer read through the router IS
            # the one that polled the batch; read lazily — the consumer
            # is rebuilt on crash-recycle). A ParallelRouter has no
            # single consumer: entries stay epoch=None, which the
            # conservation checker treats conservatively.
            self.fleet_ledger.epoch_fn = lambda: getattr(
                getattr(router, "_tx_consumer", None), "epoch", None)
        if self.replay_tap is not None:
            # replay plane (replay/): the service re-produces recorded
            # windows through THIS router under bulk admission; the tap
            # (already wrapping the audit seam) hands the replayed
            # verdicts to its parity join. Registered as a supervised
            # component so a crashed worker restarts and resumes from
            # its durable cursor.
            from ccfd_tpu.replay.service import ReplayService
            from ccfd_tpu.runtime.supervisor import RestartPolicy

            rcfg = self.cfg

            def _replay_lineage():
                fn = getattr(self.audit, "lineage_fn", None)
                return fn() if fn is not None else (None, None)

            self.replay = ReplayService(
                rcfg, self.broker, self.audit, tap=self.replay_tap,
                registry=self._registry("replay"),
                state_dir=(str(replay_spec.opt("dir", rcfg.replay_dir))
                           or None),
                overload=overload,
                lineage_fn=_replay_lineage,
            )
            self.replay.batch = max(1, int(
                replay_spec.opt("batch", rcfg.replay_batch)))
            self.replay.timeout_s = float(
                replay_spec.opt("timeout_s", rcfg.replay_timeout_s))
            self.replay.retries = max(0, int(
                replay_spec.opt("retries", rcfg.replay_retries)))
            self.replay.bulk_ceiling = min(1.0, max(0.0, float(
                replay_spec.opt("bulk_ceiling", rcfg.replay_bulk_ceiling))))
            self.replay.set_pacing(float(
                replay_spec.opt("pacing_rows_s", rcfg.replay_pacing_rows_s)))
            self.supervisor.add_thread_service(
                "replay",
                self.replay.run,
                self.replay.stop,
                policy=RestartPolicy.ALWAYS,
                reset=self.replay.reset,
            )
        if self.storage_gate is not None and hasattr(router,
                                                     "set_heal_gate"):
            # the storage pin binds even with the heal component off
            # (CCFD_HEAL=0): unverifiable params pin serving to the rules
            # tier regardless; _up_heal composes the DeviceSupervisor in
            router.set_heal_gate(self.storage_gate)
        if self.partitioner is not None and self.scorer is not None:
            # swap-vs-dispatch publish path (parallel/partition.py): arm
            # the partitioner's PublishGate with the router pool's group
            # pause barrier and route the scorer's swap_params through it,
            # so a lifecycle promotion/rollback publishing SHARDED params
            # never interleaves with a worker's in-flight SPMD dispatch
            self.partitioner.set_barrier(
                router, registry=self._registry("mesh"))
            if hasattr(self.scorer, "set_swap_gate"):
                self.scorer.set_swap_gate(self.partitioner.gate)
        self.supervisor.add_thread_service(
            "router",
            lambda: router.run(poll_timeout_s=0.02),
            router.stop,
            policy=RestartPolicy.ALWAYS,
            reset=router.reset,
        )

    def _up_heal(self, c: ComponentSpec) -> None:
        from ccfd_tpu.runtime.heal import DeviceSupervisor
        from ccfd_tpu.runtime.supervisor import RestartPolicy

        cfg = self.cfg
        # respawn rung: with the lifecycle up, respawn restores the
        # champion CHECKPOINT (serialized under the controller lock so a
        # respawn racing a rollback leaves one consistent champion tree);
        # without it, the supervisor's default re-publishes the current
        # params into fresh device buffers
        respawn_fn = (self.lifecycle.restore_champion
                      if self.lifecycle is not None else None)
        self.heal = DeviceSupervisor(
            self.scorer,
            registry=self._registry("heal"),
            breaker=getattr(self.router, "_breaker", None),
            telemetry=self.device,
            profiler=self.profiler,
            recorder=self.recorder,
            overload=self._overload,
            canary_rows=int(c.opt("canary_rows", 16)),
            canary_deadline_ms=float(
                c.opt("canary_deadline_ms", cfg.heal_canary_deadline_ms)),
            suspect_strikes=int(
                c.opt("suspect_strikes", cfg.heal_suspect_strikes)),
            probation_canaries=int(
                c.opt("probation_canaries", cfg.heal_probation_canaries)),
            parity_tol=float(c.opt("parity_tol", cfg.heal_parity_tol)),
            oom_ratio=float(c.opt("oom_ratio", cfg.heal_oom_ratio)),
            compile_storm_per_s=float(
                c.opt("compile_storm_per_s", cfg.heal_compile_storm_per_s)),
            backoff_base_s=float(
                c.opt("backoff_base_s", cfg.heal_backoff_base_s)),
            backoff_cap_s=float(
                c.opt("backoff_cap_s", cfg.heal_backoff_cap_s)),
            flap_window_s=float(
                c.opt("flap_window_s", cfg.heal_flap_window_s)),
            respawn_fn=respawn_fn,
        )
        if self.router is not None and hasattr(self.router,
                                               "set_heal_gate"):
            # quarantine pins the ladder to the host tier, ABOVE the
            # breaker: even a half-open probe can't leak to a sick device.
            # Composed with the storage pin (runtime/durability.py): an
            # unverifiable-params pin blocks the HOST tier too (it would
            # forward the same unverified tree) — rules only.
            if self.storage_gate is not None:
                from ccfd_tpu.runtime.durability import ComposedHealGate

                self.router.set_heal_gate(
                    ComposedHealGate(self.storage_gate, self.heal))
            else:
                self.router.set_heal_gate(self.heal)
        interval = float(c.opt("interval_s", cfg.heal_interval_s))
        self.supervisor.add_thread_service(
            "heal",
            lambda: self.heal.run(interval_s=interval),
            self.heal.stop,
            policy=RestartPolicy.ALWAYS,
            reset=self.heal.reset,
        )

    def _up_fleet(self, c: ComponentSpec) -> None:
        from ccfd_tpu.fleet.member import FleetMember
        from ccfd_tpu.runtime.supervisor import RestartPolicy

        cfg = self.cfg
        member = str(c.opt("member", cfg.fleet_member)
                     or f"member-{os.getpid()}")
        peers = c.opt("peers", None)
        if peers is None:
            peers = [p.strip() for p in cfg.fleet_peers.split(",")
                     if p.strip()]
        fingerprint_fn = None
        if self.scorer is not None and hasattr(self.scorer, "params"):
            from ccfd_tpu.parallel.partition import params_fingerprint

            scorer = self.scorer
            fingerprint_fn = lambda: params_fingerprint(scorer.params)  # noqa: E731
        router = self.router

        def consumers_fn():
            if router is None:
                return []
            if hasattr(router, "workers"):  # ParallelRouter pool
                return [w._tx_consumer for w in router.workers
                        if getattr(w, "_tx_consumer", None) is not None]
            tx = getattr(router, "_tx_consumer", None)
            return [tx] if tx is not None else []

        router_reg = self.registries.get("router")

        def counters_fn():
            def tot(name):
                m = (router_reg.get(name)
                     if router_reg is not None else None)
                return int(m.total()) if m is not None else 0

            return {
                "incoming": tot("transaction_incoming_total"),
                "routed": tot("transaction_outgoing_total"),
                "shed": tot("router_shed_total"),
                "errors": (tot("router_score_errors_total")
                           + tot("router_process_start_errors_total")
                           + tot("transaction_decode_errors_total")),
            }

        gmi = int(c.opt("global_max_inflight",
                        cfg.fleet_global_max_inflight))
        self.fleet = FleetMember(
            member,
            self._registry("fleet"),
            peers=peers,
            heartbeat_host=c.opt("heartbeat_host", "127.0.0.1"),
            heartbeat_port=int(
                c.opt("heartbeat_port", cfg.fleet_heartbeat_port)),
            ttl_s=float(c.opt("ttl_s", cfg.fleet_ttl_s)),
            overload=self._overload if gmi > 0 else None,
            recorder=self.recorder,
            fingerprint_fn=fingerprint_fn,
            consumers_fn=consumers_fn,
            counters_fn=counters_fn,
            global_max_inflight=gmi or None,
        )
        self.fleet.start_server()
        if router is not None and hasattr(router, "set_heal_gate"):
            # the parity gate composes with whatever already guards the
            # ladder (storage pin, device heal): ANY quarantine pins
            # DOWN, and a stale champion blocks the host tier too (the
            # host forward serves the same stale tree) — rules only
            gates = [g for g in (self.storage_gate, self.heal,
                                 self.fleet.parity_gate) if g is not None]
            if len(gates) > 1:
                from ccfd_tpu.runtime.durability import ComposedHealGate

                router.set_heal_gate(ComposedHealGate(*gates))
            else:
                router.set_heal_gate(gates[0])
        interval = float(
            c.opt("gossip_interval_s", cfg.fleet_gossip_interval_s))
        self.supervisor.add_thread_service(
            "fleet",
            lambda: self.fleet.run(interval_s=interval),
            self.fleet.stop,
            policy=RestartPolicy.ALWAYS,
            reset=self.fleet.reset,
        )

    def _up_investigator(self) -> None:
        from ccfd_tpu.process.investigator import InvestigatorService
        from ccfd_tpu.runtime.supervisor import RestartPolicy

        c = self.spec.component("investigator")
        svc = InvestigatorService(
            self.engine, self._registry("investigator"),
            rate_per_s=float(c.opt("rate_per_s", 50.0)),
            trust_threshold=float(c.opt("trust_threshold", 0.9)),
            base_fraud_rate=float(c.opt("base_fraud_rate", 0.05)),
            seed=int(c.opt("seed", 0)),
        )
        self.investigator = svc
        self.supervisor.add_thread_service(
            "investigator", svc.run, svc.stop,
            policy=RestartPolicy.ALWAYS, reset=svc.reset,
        )

    def _up_crash_recovery(self) -> None:
        """Aligned checkpoints + engine-as-supervised-service: an engine
        crash (chaos or real) restores the last cut and re-drives the
        bus through the LIVE router (runtime/recovery.py). The engine's
        other referents (this platform object, the KIE REST server)
        re-point via on_swap inside the barrier."""
        from ccfd_tpu.runtime.recovery import (
            CheckpointCoordinator,
            attach_engine_service,
        )

        c = self.spec.component("engine")

        def on_swap(engine) -> None:
            self.engine = engine
            if self.engine_server is not None:
                self.engine_server.engine = engine
            if self.investigator is not None:
                self.investigator.engine = engine

        self.recovery = CheckpointCoordinator(
            self.router, self.broker, self._engine_factory,
            interval_s=float(c.opt("checkpoint_interval_s", 5.0)),
            on_swap=on_swap,
            path=c.opt("checkpoint_file", "") or None,
        )
        from ccfd_tpu.serving.history import SeqScorer

        if isinstance(self.scorer, SeqScorer):
            # per-customer histories are pipeline state: they must reset
            # to the cut before a rewind replays records, or replay
            # double-appends every transaction (serving/history.py)
            self.recovery.register_state(
                "history", self.scorer.store.snapshot,
                self.scorer.store.restore,
            )
        # full-process crash recovery: the services haven't started yet,
        # so a persisted cut restores cleanly here — engine state from
        # the cut, the gap re-driven from the (durable) bus after start.
        # Takes precedence over the file-based `state_file` load (the cut
        # is crash-consistent with the bus; state_file is not).
        self.recovery.restore_from_disk()
        attach_engine_service(self.supervisor, self.recovery)
        self.recovery.start()

    def _up_retrain(self) -> None:
        from ccfd_tpu.parallel.online import OnlineTrainer
        from ccfd_tpu.runtime.supervisor import RestartPolicy

        c = self.spec.component("retrain")
        # governed rollout by default when the lifecycle component is up;
        # retrain.direct_swap: true keeps the legacy unvalidated hot swap
        lifecycle = (None if bool(c.opt("direct_swap", False))
                     else self.lifecycle)
        trainer = OnlineTrainer(
            self.cfg, self.broker, self.scorer, self.scorer.params,
            registry=self._registry("retrain"),
            seed=int(c.opt("seed", 0)),
            lifecycle=lifecycle,
            partitioner=self.partitioner,
        )
        if lifecycle is not None:
            # REJECT/ROLLBACK re-bases the trainer onto the champion so
            # the next candidate descends from its recorded parent
            lifecycle.trainer_rebase = trainer.rebase
        interval = float(c.opt("interval_s", 0.5))
        self.supervisor.add_thread_service(
            "retrain",
            lambda: trainer.run(interval_s=interval),
            trainer.stop,
            policy=RestartPolicy.ALWAYS,
            reset=trainer.reset,
        )

    def _up_analytics(self) -> None:
        from ccfd_tpu.analytics.engine import AnalyticsEngine, DriftMonitor
        from ccfd_tpu.runtime.supervisor import RestartPolicy

        c = self.spec.component("analytics")
        registry = self._registry("analytics")
        engine = AnalyticsEngine(
            nbins=int(c.opt("nbins", 32)), registry=registry
        )

        def build_reference():
            # dataset load + two jit compiles: runs on the supervised
            # thread so bring-up (probes, exporter, producer) isn't blocked
            from ccfd_tpu.data.ccfd import load_dataset

            ds = load_dataset()
            return engine.summarize(ds.X, ds.y)

        monitor = DriftMonitor(
            self.cfg,
            self.broker,
            None,
            engine=engine,
            registry=registry,
            window=int(c.opt("window", 4096)),
            reference_builder=build_reference,
            # persisted PSI baseline (CR analytics.reference_file): a
            # restart reloads the training-distribution histogram instead
            # of rebuilding it from an empty window
            reference_path=c.opt("reference_file", "") or None,
        )
        interval = float(c.opt("interval_s", 0.25))
        self.supervisor.add_thread_service(
            "analytics",
            lambda: monitor.run(interval_s=interval),
            monitor.stop,
            policy=RestartPolicy.ALWAYS,
            reset=monitor.reset,
        )

    def _up_producer(self) -> None:
        from ccfd_tpu.producer.producer import Producer
        from ccfd_tpu.runtime.supervisor import RestartPolicy

        c = self.spec.component("producer")
        producer = Producer(
            self.cfg, self.broker, registry=self._registry("producer"),
            store_faults=(self.fault_plan.injector(
                "store", self._registry("producer"))
                if self.fault_plan else None),
            tracer=self._tracer("producer"),
        )
        limit = c.opt("transactions")
        rate = c.opt("rate")
        wire = c.opt("wire_format", "dict")
        done = self._producer_done

        def run() -> None:
            try:
                producer.run(
                    limit=int(limit) if limit is not None else None,
                    rate_per_s=float(rate) if rate else None,
                    wire_format=wire,
                )
            finally:
                done.set()

        # one-shot job semantics, like the reference's producer pod
        self.supervisor.add_thread_service(
            "producer", run, policy=RestartPolicy.NEVER
        )
        self.supervisor.start_service("producer")

    def _wire_memory_probes(self) -> None:
        """Per-component live-object counts for the memory-drift surface
        (``ccfd_component_objects`` gauges + the /memory endpoint,
        observability/memory.py). Probes resolve through ``self`` so
        crash-recovery swaps are followed automatically."""
        ex = self.exporter
        if self.engine is not None and hasattr(self.engine, "object_counts"):
            # sum over object_counts: instances + tasks + rings
            ex.add_probe("engine", lambda: sum(
                (self.engine.object_counts() or {}).values()))
        if self.broker is not None and hasattr(self.broker,
                                               "health_snapshot"):
            def bus_retained() -> int:
                snap = self.broker.health_snapshot()
                return sum(
                    e - b
                    for t in snap["topics"]
                    for e, b in zip(snap["topics"][t], snap["begins"][t])
                )

            ex.add_probe("bus_retained_records", bus_retained)
        if self.trace_sink is not None:
            ex.add_probe("trace_sink",
                         lambda: len(self.trace_sink.traces()))
        if getattr(self.router, "batcher", None) is not None:
            ex.add_probe("router_batcher_queue",
                         lambda: self.router.batcher.qsize())
        if getattr(self.prediction_server, "batcher", None) is not None:
            # the REST-side DynamicBatcher (the queue the overload
            # codel/bound knobs police) — the Overload board charts it
            ex.add_probe("serving_batcher_queue",
                         lambda: self.prediction_server.batcher.qsize())

    # -- status / teardown -------------------------------------------------
    def wait_producer(self, timeout_s: float = 60.0) -> bool:
        return self._producer_done.wait(timeout=timeout_s)

    def status(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "services": self.supervisor.status() if self.supervisor else {},
            "endpoints": {},
        }
        if self.mesh is not None:
            out["mesh"] = {
                "devices": int(self.mesh.size),
                "axes": {str(a): int(s)
                         for a, s in self.mesh.shape.items()},
                # the CR vocabulary (replicated | rules), so live status
                # diffs cleanly against the spec that produced it
                "param_partition": getattr(
                    self, "_mesh_param_partition", "replicated"),
                "seq_parallel": getattr(self, "_mesh_seq_parallel", "none"),
            }
        if self.replay is not None:
            out["replay"] = {
                "bulk_ceiling": self.replay.bulk_ceiling,
                "pacing_rows_s": self.replay.pacing_rows_s,
                "batch": self.replay.batch,
                "last_report": self.replay.last_report,
            }
        if self.store_server:
            out["endpoints"]["store"] = self.store_server.endpoint
        if self.prediction_server:
            out["endpoints"]["scorer"] = (
                f"http://{self.prediction_host}:{self.prediction_port}"
            )
        if self.exporter:
            out["endpoints"]["metrics"] = self.exporter.endpoint
        if self.health_server:
            out["endpoints"]["health"] = self.health_server.endpoint
        return out

    def _health_verdict(self) -> dict[str, Any]:
        """One strict-JSON readiness verdict for the exporter's /healthz:
        every health-bearing plane that is actually up contributes a
        source with a cause string; absent planes (kill-switched or never
        built) are simply not listed, so a minimal platform is not
        "degraded" for lacking optional components."""
        import time

        sources: dict[str, dict[str, Any]] = {}

        def add(name: str, healthy: bool, cause: str) -> None:
            sources[name] = {"healthy": bool(healthy), "cause": cause}

        if self.supervisor is not None:
            bad = []
            for name, st in self.supervisor.status().items():
                if st.get("ready"):
                    continue
                err = st.get("last_error") or ""
                bad.append(f"{name}={st.get('state')}"
                           + (f" ({err})" if err else ""))
            add("supervisor",
                not bad,
                "; ".join(bad) if bad else "all services ready")
        if self.heal is not None:
            hst = self.heal.status()
            state = str(hst.get("state", ""))
            reasons = hst.get("reasons") or []
            add("device",
                state not in ("quarantined",),
                f"state={state}"
                + (f" ({'; '.join(str(r) for r in reasons)})"
                   if reasons and state != "healthy" else ""))
        if self.storage_gate is not None:
            add("storage",
                not self.storage_gate.pinned,
                (f"pinned to rules tier: {self.storage_gate.reason}"
                 if self.storage_gate.pinned else "verified"))
        if self.fleet is not None:
            gate = getattr(self.fleet, "parity_gate", None)
            if gate is not None:
                add("fleet",
                    not gate.quarantined,
                    "parity quarantined" if gate.quarantined
                    else "parity clean")
        breaker = getattr(self.router, "_breaker", None)
        if breaker is not None:
            bstate = breaker.state
            add("scorer_edge",
                bstate != "open",
                f"breaker={bstate}")

        causes = [f"{n}: {s['cause']}"
                  for n, s in sources.items() if not s["healthy"]]
        return {
            "healthy": not causes,
            "generated_unix": time.time(),
            "sources": sources,
            "causes": causes,
        }

    def _save_engine_state(self) -> None:
        if self._engine_state_file:
            try:
                self.engine.save(self._engine_state_file)
            except Exception:  # noqa: BLE001 - persistence must not kill the host
                logging.getLogger(__name__).exception(
                    "engine state save to %s failed; process state will NOT "
                    "survive a restart", self._engine_state_file,
                )
        if getattr(self, "_usertask_state_file", None) and self.usertask_model:
            try:
                self.usertask_model.save(self._usertask_state_file)
            except Exception:  # noqa: BLE001
                logging.getLogger(__name__).exception(
                    "user-task model save to %s failed", self._usertask_state_file
                )

    def down(self) -> None:
        # chaos first: injecting failures into services that are being torn
        # down would race the orderly shutdown
        if self.chaos is not None:
            self.chaos.stop()
        if self.device_fault_plan is not None:
            # the plan installed PROCESS-wide; a torn-down platform must
            # not leave standing device faults for the next one in-process
            from ccfd_tpu.runtime.faults import install_device_faults

            install_device_faults(None)
            self.device_fault_plan = None
        if self.storage_fault_plan is not None:
            from ccfd_tpu.runtime.faults import install_storage_faults

            install_storage_faults(None)
            self.storage_fault_plan = None
        from ccfd_tpu.runtime import durability

        durability.set_recorder(None)
        if self.recovery is not None:
            self.recovery.stop()
        if self.supervisor:
            self.supervisor.stop()
        if self.audit is not None:
            # the supervised flusher's shutdown already lands the tail;
            # this covers platforms torn down before the supervisor ran
            try:
                self.audit.flush()
            except Exception:  # noqa: BLE001 - teardown must not raise
                pass
        if self.lifecycle is not None:
            try:
                self.lifecycle.close()  # releases the evaluator consumers
            except Exception:  # noqa: BLE001
                pass
        if self.fleet is not None:
            try:
                self.fleet.close()  # heartbeat server + peer clients
            except Exception:  # noqa: BLE001
                pass
        if self._broker_is_client and self.broker is not None:
            # a bus-client broker owns sockets to the SHARED bus server;
            # the in-process Broker is left alone (its segment logs are
            # torn down with the process, matching historical behavior)
            try:
                self.broker.close()
            except Exception:  # noqa: BLE001
                pass
        # a ParallelRouter owns coalescing-batcher threads the supervisor
        # doesn't know about; release any callers still parked on futures
        if getattr(self.router, "batcher", None) is not None:
            try:
                self.router.batcher.stop()
            except Exception:  # noqa: BLE001
                pass
        if self.engine is not None and (
            getattr(self, "_engine_state_file", None)
            or getattr(self, "_usertask_state_file", None)
        ):
            self._save_engine_state()
        for srv in (
            self.prediction_server,
            self.engine_server,
            self.exporter,
            self.health_server,
            self.store_server,
        ):
            if srv is not None:
                try:
                    srv.stop()
                except Exception:  # noqa: BLE001
                    pass
        self._up = False
