"""Prometheus scrape endpoint over the framework's metric registries.

The reference wires Prometheus to each service by pod annotation — model
``/prometheus`` (reference README.md:292-301), router ``:8091/prometheus``
(README.md:503-507), KIE ``:8090/rest/metrics`` (README.md:509-515). When
the pipeline runs in one process under the platform operator, this exporter
serves every component registry from one port, preserving the per-service
paths so the reference's scrape configs (deploy/prometheus.yaml here) remap
1:1:

    GET /prometheus            all registries concatenated
    GET /prometheus/<name>     one component (router, kie, notify, ...)
    GET /rest/metrics          alias for the KIE registry (reference path)
    GET /traces                retained-trace summaries (tail sampler, JSON)
    GET /traces/<id>           one retained trace's spans (JSON)
    GET /profile               live StageProfile document (JSON): per-stage
                               queueing/service/dispatch decomposition +
                               batch-conditioned service curves + compile
                               attribution — the provisioning-planner input
                               contract (observability/profile.py)
    GET /memory                memory-drift evidence (JSON): RSS, GC stats,
                               per-component object counts, tracemalloc top
                               allocators; ?trace=1 arms tracemalloc
                               (observability/memory.py)
    GET /incidents             incident-bundle summaries (JSON), newest
                               first (observability/incident.py)
    GET /incidents/<id>        one full schema-validated incident bundle
                               (JSON); unknown ids 404
    GET /decisions             decision-record summaries (JSON), newest
                               first; ?since=<unix_ts>&until=<unix_ts>
                               bracket decide time, ?limit=N bounds the
                               page (observability/audit.py)
    GET /decisions/<tx_id>     one full DecisionRecord by transaction id
                               (or "partition:offset" uid); unknown ids
                               404 — strict JSON either way, and both
                               endpoints 404 entirely when the audit
                               plane is off (CCFD_AUDIT=0)
    GET /capacity              fitted capacity-model document (JSON,
                               schema ccfd.capacity.v1): per-stage
                               utilization/headroom/knee, predicted vs
                               observed p50/p99, bottleneck attribution
                               (observability/capacity.py); 404s entirely
                               when the plane is off (CCFD_CAPACITY=0)
    GET /capacity/whatif?workers=&batch=&deadline_ms=&max_inflight=
                               the same document re-evaluated under the
                               requested actuator overrides, with a
                               `whatif` section carrying the predicted-p99
                               delta — nothing live is touched
    GET /healthz               one-stop readiness rollup (strict JSON):
                               200 healthy / 503 degraded, composed from
                               supervisor service states, device health,
                               the storage pin gate, fleet parity and the
                               scorer-edge breaker with per-source cause
                               strings; 404 when no health composer is
                               wired (standalone harnesses)
    GET /debug/device          live device-telemetry snapshot (JSON):
                               per-device memory, measured H2D accounting,
                               executable inventory (observability/device.py)
    GET /debug/profile?seconds=N   on-demand jax.profiler device capture:
                               blocks ~N seconds (clamped to 60), returns
                               {"trace_dir": ...} with the TensorBoard
                               trace; one capture at a time (409-style
                               {"error": ...} body while busy)

Contract details (scrapers depend on them): metric paths answer with
``Content-Type: text/plain; version=0.0.4`` — or the OpenMetrics format
(with histogram exemplars carrying trace ids) when the Accept header asks
for ``application/openmetrics-text``; unknown registry names 404; HEAD
mirrors GET headers with no body (liveness probes use it).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler

from ccfd_tpu.utils.httpserver import FrameworkHTTPServer

from ccfd_tpu.metrics.prom import Registry

_TEXT_CTYPE = "text/plain; version=0.0.4"
_OPENMETRICS_CTYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"


def _merge_renders(bodies: list[str], openmetrics: bool) -> str:
    """Concatenate per-registry expositions into ONE valid exposition.

    Naive concatenation breaks both formats once a metric family appears
    in more than one registry (every component registry now carries
    ``trace_span_seconds`` and the labelset-guard counter): duplicate
    HELP/TYPE headers, families reopened later in the stream, and — for
    OpenMetrics — ``# EOF`` markers mid-body. Merge family-wise instead:
    each family's metadata is emitted once (first registry's wins), every
    registry's samples group under it, IDENTICAL series from different
    registries combine (counter/histogram samples sum — they are counts;
    gauges last-write-wins; first exemplar kept), and the OM terminator
    is appended exactly once at the end."""
    order: list[str] = []
    meta: dict[str, list[str]] = {}
    kind_of: dict[str, str] = {}
    # family -> {series key ("name{labels}"): [value, trailer]} in order
    series: dict[str, dict[str, list]] = {}
    seen_meta: set[tuple[str, str]] = set()

    def family_of(name: str) -> dict[str, list]:
        if name not in meta:
            meta[name] = []
            series[name] = {}
            order.append(name)
        return series[name]

    for body in bodies:
        family = ""  # Registry.render always emits TYPE before samples;
        family_of("")  # "" is a defensive bucket for stray preamble lines
        for line in body.splitlines():
            if line == "# EOF" or not line:
                continue
            if line.startswith(("# HELP ", "# TYPE ")):
                kind, name = line.split(" ", 3)[1:3]
                family_of(name)
                family = name
                if line.startswith("# TYPE "):
                    kind_of.setdefault(name, line.rsplit(" ", 1)[1])
                if (name, kind) not in seen_meta:  # first registry wins
                    seen_meta.add((name, kind))
                    meta[name].append(line)
            else:
                fam = family_of(family)
                head, _, trailer = line.partition(" # ")
                key, _, val = head.rpartition(" ")
                prev = fam.get(key)
                if prev is None:
                    fam[key] = [val, trailer]
                else:
                    # same series from another registry: counters and
                    # histogram counts are additive; gauges last-wins
                    try:
                        if kind_of.get(family) == "gauge":
                            prev[0] = val
                        else:
                            total = float(prev[0]) + float(val)
                            prev[0] = (str(int(total))
                                       if prev[0].isdigit() and val.isdigit()
                                       else repr(total))
                    except ValueError:
                        prev[0] = val  # unparseable (+Inf etc.): last wins
                    if not prev[1]:
                        prev[1] = trailer
    out: list[str] = []
    for name in order:
        out.extend(meta.get(name, ()))
        for key, (val, trailer) in series.get(name, {}).items():
            out.append(f"{key} {val}" + (f" # {trailer}" if trailer else ""))
    if openmetrics:
        out.append("# EOF")
    return "\n".join(out) + "\n"


class MetricsExporter:
    def __init__(self, registries: dict[str, Registry],
                 host: str = "127.0.0.1", port: int = 0,
                 sink=None,
                 memory_probes: dict[str, "object"] | None = None,
                 profiler=None,
                 telemetry=None,
                 recorder=None,
                 audit=None,
                 capacity=None,
                 health=None):
        self._registries = dict(registries)
        self._sink = sink  # observability.trace.SpanSink (or None)
        self._profiler = profiler  # observability.profile.StageProfiler
        self._telemetry = telemetry  # observability.device.DeviceTelemetry
        self._recorder = recorder  # observability.incident.FlightRecorder
        self._audit = audit  # observability.audit.AuditLog
        self._capacity = capacity  # observability.capacity.CapacityModel
        self._health = health  # callable -> readiness doc (see healthz())
        self._capture_lock = threading.Lock()  # one device capture at a time
        self._lock = threading.Lock()
        # memory-drift surface (observability/memory.py): a "process"
        # registry every scrape refreshes with the RSS gauge and one
        # object-count gauge series per registered probe — the flat-memory
        # evidence the endurance soaks assert over, on the same scrape
        # Prometheus already collects
        self._memory_probes: dict[str, object] = dict(memory_probes or {})
        self._process_registry = Registry()
        self._g_rss = self._process_registry.gauge(
            "ccfd_process_rss_bytes", "process resident set size")
        self._g_objects = self._process_registry.gauge(
            "ccfd_component_objects",
            "live objects held per component container (memory probes)")
        self._registries.setdefault("process", self._process_registry)
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args) -> None:
                pass

            def _answer(self, head_only: bool) -> None:
                path = self.path.split("?")[0].rstrip("/")
                query = self.path.partition("?")[2]
                openmetrics = "application/openmetrics-text" in (
                    self.headers.get("Accept") or ""
                )
                if path == "/healthz":
                    # the one path whose STATUS CODE is the verdict: load
                    # balancers and probes read 200/503, not the body
                    body, status = exporter.healthz()
                    ctype = "application/json"
                else:
                    body, ctype = exporter.respond(path, openmetrics, query)
                    status = 200
                if body is None:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                data = body.encode()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                if not head_only:
                    self.wfile.write(data)

            def do_GET(self) -> None:
                self._answer(head_only=False)

            def do_HEAD(self) -> None:
                self._answer(head_only=True)

        self._httpd = FrameworkHTTPServer((host, port), Handler)
        self._thread: threading.Thread | None = None

    def add(self, name: str, registry: Registry) -> None:
        with self._lock:
            self._registries[name] = registry

    def add_probe(self, component: str, count_fn) -> None:
        """Register a live-object-count callable for the memory surface
        (``ccfd_component_objects{component=...}`` + /memory)."""
        with self._lock:
            self._memory_probes[component] = count_fn

    def _refresh_memory_gauges(self) -> None:
        from ccfd_tpu.observability.memory import rss_bytes

        self._g_rss.set(rss_bytes())
        with self._lock:
            probes = dict(self._memory_probes)
        for name, fn in probes.items():
            try:
                self._g_objects.set(float(fn()), labels={"component": name})
            except Exception:  # noqa: BLE001 - a dead probe must not 500
                self._g_objects.set(-1.0, labels={"component": name})

    # -- routing -----------------------------------------------------------
    def respond(self, path: str, openmetrics: bool = False,
                query: str = "") -> tuple[str | None, str]:
        """-> (body or None for 404, content type)."""
        if path == "/traces" or path.startswith("/traces/"):
            return self._traces(path), "application/json"
        if path == "/profile":
            if self._profiler is None:
                return None, "application/json"
            return (json.dumps(self._profiler.snapshot()),
                    "application/json")
        if path == "/incidents" or path.startswith("/incidents/"):
            return self._incidents(path), "application/json"
        if path == "/decisions" or path.startswith("/decisions/"):
            return self._decisions(path, query), "application/json"
        if path == "/capacity" or path == "/capacity/whatif":
            return self._capacity_doc(path, query), "application/json"
        if path == "/debug/device":
            if self._telemetry is None:
                return None, "application/json"
            return (json.dumps(self._telemetry.snapshot()),
                    "application/json")
        if path == "/debug/profile":
            return self._device_capture(query), "application/json"
        if path == "/memory":
            return self._memory(query), "application/json"
        body = self.render_path(path, openmetrics)
        return body, (_OPENMETRICS_CTYPE if openmetrics else _TEXT_CTYPE)

    def _memory(self, query: str) -> str:
        from urllib.parse import parse_qs

        from ccfd_tpu.observability.memory import (
            ensure_tracemalloc,
            memory_report,
        )

        if parse_qs(query or "").get("trace") == ["1"]:
            # arming is explicit — tracemalloc costs ~2x allocation
            # overhead while on, which an always-on scrape must not pay
            ensure_tracemalloc()
        with self._lock:
            probes = dict(self._memory_probes)
        return json.dumps(memory_report(probes))

    def _incidents(self, path: str) -> str | None:
        if self._recorder is None:
            return None
        if path.rstrip("/") == "/incidents":
            return json.dumps({"incidents": self._recorder.incidents()})
        doc = self._recorder.incident_doc(path[len("/incidents/"):])
        if doc is None:
            return None
        return json.dumps(doc)

    def _decisions(self, path: str, query: str) -> str | None:
        """Decision-provenance queries (observability/audit.py). With the
        plane off (CCFD_AUDIT=0 -> no AuditLog wired) BOTH endpoints 404
        — the kill-switch contract, like /debug/* under CCFD_DEVICE=0."""
        if self._audit is None:
            return None
        if path.rstrip("/") == "/decisions":
            from urllib.parse import parse_qs

            q = parse_qs(query or "")
            since = until = None
            try:
                if q.get("since"):
                    since = float(q["since"][0])
            except ValueError:
                since = None
            try:
                if q.get("until"):
                    until = float(q["until"][0])
            except ValueError:
                until = None
            try:
                limit = int((q.get("limit") or ["256"])[0])
            except ValueError:
                limit = 256
            return json.dumps(
                {"decisions": self._audit.list(since=since, until=until,
                                               limit=limit)})
        rec = self._audit.get(path[len("/decisions/"):])
        if rec is None:
            return None
        return json.dumps(rec)

    def _capacity_doc(self, path: str, query: str) -> str | None:
        """Capacity-model documents (observability/capacity.py). With the
        plane off (CCFD_CAPACITY=0 -> no model wired) BOTH endpoints 404
        — the kill-switch contract, like /decisions under CCFD_AUDIT=0."""
        if self._capacity is None:
            return None
        if path == "/capacity":
            return json.dumps(self._capacity.snapshot())
        from urllib.parse import parse_qs

        q = parse_qs(query or "")

        def _int(name: str) -> int | None:
            try:
                return int(q[name][0]) if q.get(name) else None
            except ValueError:
                return None

        def _float(name: str) -> float | None:
            try:
                return float(q[name][0]) if q.get(name) else None
            except ValueError:
                return None

        return json.dumps(self._capacity.whatif(
            workers=_int("workers"), batch=_int("batch"),
            deadline_ms=_float("deadline_ms"),
            max_inflight=_int("max_inflight")))

    def healthz(self) -> tuple[str | None, int]:
        """/healthz readiness rollup -> (body, status): None/404 when no
        health composer is wired (standalone harnesses), else the
        composed verdict document with 200 healthy / 503 degraded."""
        if self._health is None:
            return None, 404
        try:
            doc = self._health()
        # ccfd-lint: disable=counted-drops -- the degraded 503 body carries the probe failure as its cause string
        except Exception as e:  # noqa: BLE001 - a probe bug reads degraded
            doc = {"healthy": False, "sources": {},
                   "causes": [f"health composer error: {e!r}"[:200]]}
        return json.dumps(doc), (200 if doc.get("healthy") else 503)

    def _device_capture(self, query: str) -> str | None:
        """On-demand jax.profiler trace (/debug/profile?seconds=N): the
        deep device-level view behind the always-on stage profile. Blocks
        the (threaded) handler for ~N seconds; captures are serialized —
        jax.profiler.trace is not reentrant. Part of the DEVICE plane's
        contract: CCFD_DEVICE=0 (telemetry absent) 404s it like
        /debug/device, even when the slo profiler is still armed."""
        if self._profiler is None or self._telemetry is None:
            return None
        import tempfile
        import time as _time
        from urllib.parse import parse_qs

        q = parse_qs(query or "")
        try:
            seconds = float((q.get("seconds") or ["3"])[0])
        except ValueError:
            seconds = 3.0
        seconds = min(max(seconds, 0.05), 60.0)
        if not self._capture_lock.acquire(blocking=False):
            return json.dumps({"error": "device capture already in progress"})
        try:
            logdir = tempfile.mkdtemp(prefix="ccfd_device_trace_")
            with self._profiler.profile_device(logdir):
                _time.sleep(seconds)
            return json.dumps({"trace_dir": logdir, "seconds": seconds})
        except Exception as e:  # noqa: BLE001 - a debug endpoint must not 500
            return json.dumps({"error": repr(e)[:200]})
        finally:
            self._capture_lock.release()

    def render_path(self, path: str, openmetrics: bool = False) -> str | None:
        # the scrape is the sampling clock for the memory gauges: every
        # metric render refreshes RSS + component object counts first —
        # and for the stage-latency gauges (the SLO board's decomposition
        # panels must read fresh quantiles, not the last /profile read's)
        self._refresh_memory_gauges()
        if self._profiler is not None:
            try:
                self._profiler.refresh_gauges()
            except Exception:  # noqa: BLE001 - a profiler bug must not 500
                pass
        if self._telemetry is not None:
            # same contract as RSS: the scrape is the sampling clock for
            # the per-device memory gauges
            try:
                self._telemetry.refresh()
            except Exception:  # noqa: BLE001 - telemetry must not 500
                pass
        with self._lock:
            regs = dict(self._registries)
        if path in ("", "/prometheus", "/metrics"):
            return _merge_renders(
                [r.render(openmetrics=openmetrics) for r in regs.values()],
                openmetrics,
            )
        if path == "/rest/metrics":  # reference KIE scrape path
            kie = regs.get("kie")
            return kie.render(openmetrics=openmetrics) if kie else None
        if path.startswith("/prometheus/"):
            r = regs.get(path[len("/prometheus/"):])
            return r.render(openmetrics=openmetrics) if r else None
        return None

    def _traces(self, path: str) -> str | None:
        if self._sink is None:
            return None
        if path == "/traces":
            return json.dumps({"traces": self._sink.traces()})
        trace_id = path[len("/traces/"):]
        spans = self._sink.trace(trace_id)
        if spans is None:
            return None
        return json.dumps({"trace_id": trace_id, "spans": spans})

    @property
    def endpoint(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "MetricsExporter":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="ccfd-metrics"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
