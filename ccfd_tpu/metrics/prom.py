"""Minimal thread-safe Prometheus metrics with text exposition.

The reference's observability contract is the union of six Grafana boards
(reference deploy/grafana/*.json) scraping Prometheus endpoints exposed per
service (reference README.md:487-537). This module reimplements exactly what
those boards need — Counter, Gauge, Histogram with labels, rendered in the
Prometheus text format — with no global state (each service owns a Registry,
so tests can run many pipelines in one process).

Metric names used across the framework mirror the reference:
- router counters ``transaction_incoming_total``,
  ``transaction_outgoing_total{type=...}``, ``notifications_outgoing_total``,
  ``notifications_incoming_total{response=...}`` (README.md:522-530,
  Router.json:88,163,250,326)
- KIE amount histograms ``fraud_investigation_amount`` etc. (README.md:532-537)
- model gauges ``proba_1``/``Amount``/``V17``/``V10`` (ModelPrediction.json:96-104)
- Seldon-style request/latency series (SeldonCore.json:119-531).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Iterable, Mapping, Sequence

LabelKey = tuple[tuple[str, str], ...]

# Label-cardinality guard (round 7): the trace layer labels series by span/
# edge/component, and a bug (or an attacker-controlled label value) must
# never be able to blow up the scrape surface. Each metric admits at most
# ``labelset_limit`` distinct label-sets; extra label-sets fold into ONE
# overflow series so the signal degrades to "something overflowed" instead
# of an unbounded /metrics body, and the registry counts the folds in
# ``ccfd_metric_labelsets_dropped_total{metric=...}``.
DEFAULT_LABELSET_LIMIT = 512
OVERFLOW_KEY: LabelKey = (("overflow", "true"),)
LABELSETS_DROPPED = "ccfd_metric_labelsets_dropped_total"


def _labelkey(labels: Mapping[str, str] | None) -> LabelKey:
    if not labels:
        return ()
    return tuple(sorted(labels.items()))


def _escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(key: LabelKey) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in key)
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    return repr(float(v))


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str = "",
                 labelset_limit: int | None = None):
        self.name = name
        self.help = help_
        self.labelset_limit = (DEFAULT_LABELSET_LIMIT
                               if labelset_limit is None
                               else int(labelset_limit))
        self._lock = threading.Lock()
        # set by Registry._get_or_make so folds are counted on the same
        # scrape surface; directly-constructed metrics stay bounded but
        # uncounted
        self._on_overflow = None

    def _admit(self, key: LabelKey, known: Mapping[LabelKey, object]) -> LabelKey:
        """Call under self._lock: the guarded key for a write. Existing
        series and the unlabeled series always pass; a NEW series past the
        limit folds into the overflow bucket."""
        if not key or key in known or len(known) < self.labelset_limit:
            return key
        if self._on_overflow is not None:
            self._on_overflow(self.name)
        return OVERFLOW_KEY

    def render(self) -> Iterable[str]:  # pragma: no cover - interface
        raise NotImplementedError


class _ScalarMetric(_Metric):
    """Shared labeled-scalar storage for Counter and Gauge."""

    def __init__(self, name: str, help_: str = "",
                 labelset_limit: int | None = None):
        super().__init__(name, help_, labelset_limit)
        self._values: dict[LabelKey, float] = {}

    def inc(self, amount: float = 1.0, labels: Mapping[str, str] | None = None) -> None:
        key = _labelkey(labels)
        with self._lock:
            key = self._admit(key, self._values)
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, labels: Mapping[str, str] | None = None) -> float:
        with self._lock:
            return self._values.get(_labelkey(labels), 0.0)

    def total(self) -> float:
        """Sum across every label set — ``sum(metric)`` in PromQL terms
        (e.g. worker-labelled batch counters pooled for a scaling ratio)."""
        with self._lock:
            return sum(self._values.values())

    def items(self) -> list[tuple[LabelKey, float]]:
        """Every (labelkey, value) pair — read-side enumeration for
        consumers that aggregate across label sets (the stage profiler's
        overload section, the SLO engine's error-class sums)."""
        with self._lock:
            return list(self._values.items())

    def render(self) -> Iterable[str]:
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            yield f"{self.name}{_fmt_labels(key)} {_fmt_value(v)}"


class Counter(_ScalarMetric):
    kind = "counter"

    def inc(self, amount: float = 1.0, labels: Mapping[str, str] | None = None) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        super().inc(amount, labels)


class Gauge(_ScalarMetric):
    kind = "gauge"

    def set(self, value: float, labels: Mapping[str, str] | None = None) -> None:
        key = _labelkey(labels)
        with self._lock:
            self._values[self._admit(key, self._values)] = float(value)


DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.5, 5.0, 10.0, math.inf,
)

# Amount histograms on the KIE board span transaction amounts, not seconds
# (reference KIE.json bucket panels; README.md:532-537).
AMOUNT_BUCKETS = (
    1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0,
    5000.0, 10000.0, math.inf,
)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help_: str = "",
        buckets: Sequence[float] = DEFAULT_BUCKETS,
        labelset_limit: int | None = None,
    ):
        super().__init__(name, help_, labelset_limit)
        b = sorted(set(float(x) for x in buckets))
        if not b or b[-1] != math.inf:
            b.append(math.inf)
        self.buckets = tuple(b)
        self._counts: dict[LabelKey, list[int]] = {}
        self._sums: dict[LabelKey, float] = {}
        # last exemplar per (labelset, bucket): OpenMetrics exemplars tie a
        # trace id to the histogram cell the observation landed in, so a
        # Grafana heat map links to the exact retained trace
        # (observability/trace.py; exporter /traces/<id>)
        self._exemplars: dict[LabelKey, dict[int, tuple[dict, float, float]]] = {}

    def observe(
        self,
        value: float,
        labels: Mapping[str, str] | None = None,
        exemplar: Mapping[str, str] | None = None,
    ) -> None:
        key = _labelkey(labels)
        with self._lock:
            key = self._admit(key, self._counts)
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            bucket_i = len(self.buckets) - 1
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    counts[i] += 1
                    bucket_i = min(bucket_i, i)
            self._sums[key] = self._sums.get(key, 0.0) + float(value)
            if exemplar:
                self._exemplars.setdefault(key, {})[bucket_i] = (
                    dict(exemplar), float(value), time.time()
                )

    def observe_many(
        self, values, labels: Mapping[str, str] | None = None
    ) -> None:
        """Vectorized observe: one numpy pass per batch instead of a
        Python loop per record. The router's decision-latency series
        observes every transaction in a micro-batch at once — at 100k+
        tx/s a per-record ``observe`` would be a pipeline bottleneck."""
        import numpy as np

        arr = np.sort(np.asarray(values, dtype=np.float64))
        if arr.size == 0:
            return
        cums = [
            int(np.searchsorted(arr, ub, side="right"))
            if ub != math.inf else int(arr.size)
            for ub in self.buckets
        ]
        self.merge_counts(cums, float(arr.sum()), labels)

    def merge_counts(
        self,
        bucket_counts: Sequence[int],
        sum_: float,
        labels: Mapping[str, str] | None = None,
    ) -> None:
        """Fold externally-observed cumulative le-counts into this series.

        For native-code observers (the C++ serving front scores requests
        without touching Python) that accumulate in the SAME bucket layout:
        the caller passes per-bucket DELTAS since its last fold plus the
        matching latency-sum delta. Layout mismatch is a programming error
        and raises rather than corrupting the series.
        """
        if len(bucket_counts) != len(self.buckets):
            raise ValueError(
                f"bucket layout mismatch: got {len(bucket_counts)} counts "
                f"for {len(self.buckets)} buckets"
            )
        key = _labelkey(labels)
        with self._lock:
            key = self._admit(key, self._counts)
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, c in enumerate(bucket_counts):
                counts[i] += int(c)
            self._sums[key] = self._sums.get(key, 0.0) + float(sum_)

    def count(self, labels: Mapping[str, str] | None = None) -> int:
        with self._lock:
            counts = self._counts.get(_labelkey(labels))
            return counts[-1] if counts else 0

    def sum(self, labels: Mapping[str, str] | None = None) -> float:
        with self._lock:
            return self._sums.get(_labelkey(labels), 0.0)

    def count_le(self, value: float,
                 labels: Mapping[str, str] | None = None) -> float:
        """Interpolated cumulative count of observations <= ``value`` —
        the inverse of :meth:`quantile`. The SLO engine derives good/bad
        event counts from latency histograms with it: good = count_le(
        target), bad = count - good. ``value`` rarely sits on a bucket
        boundary, so the within-bucket share interpolates linearly (same
        assumption histogram_quantile() makes)."""
        with self._lock:
            counts = list(self._counts.get(_labelkey(labels), []))
        return self._count_le_of(counts, value)

    def _count_le_of(self, counts: list, value: float) -> float:
        if not counts:
            return 0.0
        prev_ub, prev_c = 0.0, 0
        for ub, c in zip(self.buckets, counts):
            if value <= ub:
                if ub == math.inf:
                    return float(prev_c)
                span = ub - prev_ub
                frac = (value - prev_ub) / span if span > 0 else 1.0
                return prev_c + (c - prev_c) * frac
            prev_ub, prev_c = ub, c
        return float(counts[-1])

    def total_count(self) -> int:
        """Observation count summed across every label set (the serving
        latency series is labeled by endpoint; an SLO over "all requests"
        must see all of them)."""
        with self._lock:
            return sum(c[-1] for c in self._counts.values())

    def total_count_le(self, value: float) -> float:
        """:meth:`count_le` summed across every label set."""
        with self._lock:
            all_counts = [list(c) for c in self._counts.values()]
        return sum(self._count_le_of(c, value) for c in all_counts)

    def quantile(self, q: float, labels: Mapping[str, str] | None = None) -> float:
        """Bucket-interpolated quantile (what histogram_quantile() computes)."""
        with self._lock:
            counts = list(self._counts.get(_labelkey(labels), []))
        if not counts or counts[-1] == 0:
            return float("nan")
        total = counts[-1]
        rank = q * total
        prev_ub, prev_c = 0.0, 0
        for ub, c in zip(self.buckets, counts):
            if c >= rank:
                if ub == math.inf:
                    return prev_ub
                span = c - prev_c
                frac = (rank - prev_c) / span if span else 1.0
                return prev_ub + (ub - prev_ub) * frac
            prev_ub, prev_c = ub, c
        return prev_ub

    def render(self, exemplars: bool = False) -> Iterable[str]:
        with self._lock:
            items = sorted(self._counts.items())
            sums = dict(self._sums)
            exs = ({k: dict(v) for k, v in self._exemplars.items()}
                   if exemplars else {})
        for key, counts in items:
            key_exs = exs.get(key, {})
            for i, (ub, c) in enumerate(zip(self.buckets, counts)):
                lk = key + (("le", _fmt_value(ub)),)
                line = f"{self.name}_bucket{_fmt_labels(tuple(sorted(lk)))} {c}"
                ex = key_exs.get(i)
                if ex is not None:
                    ex_labels, ex_value, ex_ts = ex
                    line += (f" # {_fmt_labels(_labelkey(ex_labels))} "
                             f"{_fmt_value(ex_value)} {ex_ts:.3f}")
                yield line
            yield f"{self.name}_sum{_fmt_labels(key)} {_fmt_value(sums.get(key, 0.0))}"
            yield f"{self.name}_count{_fmt_labels(key)} {counts[-1]}"


class Registry:
    """Per-service metric registry; renders the /prometheus scrape body."""

    def __init__(self) -> None:
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()
        # the cardinality guard's fold counter is a metric like any other
        # (rendered on the same scrape), created eagerly so alert rules can
        # reference it before the first overflow ever happens
        self._labelsets_dropped = Counter(
            LABELSETS_DROPPED,
            "new label-sets folded into the overflow bucket, by metric",
        )
        self._metrics[LABELSETS_DROPPED] = self._labelsets_dropped

    def _note_overflow(self, metric_name: str) -> None:
        self._labelsets_dropped.inc(labels={"metric": metric_name})

    def counter(self, name: str, help_: str = "",
                labelset_limit: int | None = None) -> Counter:
        return self._get_or_make(
            name, lambda: Counter(name, help_, labelset_limit), Counter)

    def gauge(self, name: str, help_: str = "",
              labelset_limit: int | None = None) -> Gauge:
        return self._get_or_make(
            name, lambda: Gauge(name, help_, labelset_limit), Gauge)

    def histogram(
        self, name: str, help_: str = "",
        buckets: Sequence[float] = DEFAULT_BUCKETS,
        labelset_limit: int | None = None,
    ) -> Histogram:
        return self._get_or_make(
            name, lambda: Histogram(name, help_, buckets, labelset_limit),
            Histogram)

    def get(self, name: str) -> "_Metric | None":
        """A registered metric by name, or None — the read-side lookup the
        SLO engine and stage profiler resolve metric sources with (they
        consume other components' registries without knowing types up
        front)."""
        with self._lock:
            return self._metrics.get(name)

    def _get_or_make(self, name, factory, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                m._on_overflow = self._note_overflow
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered as {m.kind}")
            return m

    def render(self, openmetrics: bool = False) -> str:
        """Prometheus text exposition; ``openmetrics=True`` additionally
        renders histogram exemplars (``# {trace_id="..."} v ts``) — the
        only exposition format Prometheus ingests exemplars from. The
        exporter negotiates it via the Accept header."""
        lines: list[str] = []
        with self._lock:
            metrics = sorted(self._metrics.items())
        for name, m in metrics:
            family = name
            if openmetrics and m.kind == "counter" and name.endswith("_total"):
                # OpenMetrics names the counter FAMILY without the _total
                # suffix (samples keep it); a family named *_total is a
                # "clashing name" parse error that loses the whole scrape
                family = name[: -len("_total")]
            if m.help:
                lines.append(f"# HELP {family} {m.help}")
            lines.append(f"# TYPE {family} {m.kind}")
            if openmetrics and isinstance(m, Histogram):
                lines.extend(m.render(exemplars=True))
            else:
                lines.extend(m.render())
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"
