"""Transaction producer: dataset -> bus topic (the reference's Kafka producer).

The reference S2I-builds a Python producer that reads ``creditcard.csv`` from
Ceph S3 and streams rows to topic ``odh-demo`` (reference
deploy/kafka/ProducerDeployment.yaml:39,77-97, README.md:461-485). Here the
source is the dataset loader (local CSV via ``filename`` / CCFD_CSV, or the
synthetic stream) and the sink is the bus; an optional rate limit emulates
live traffic for latency measurements.
"""

from __future__ import annotations

import time

from ccfd_tpu.bus.broker import Broker
from ccfd_tpu.config import Config
from ccfd_tpu.data.ccfd import (
    Dataset,
    iter_transactions,
    load_csv_bytes,
    load_dataset,
)
from ccfd_tpu.metrics.prom import Registry


def dataset_from_store(cfg: Config, limit: int | None = None,
                       faults=None, breaker=None, tracer=None) -> Dataset:
    """Fetch ``filename`` from ``s3bucket`` at ``s3endpoint`` — exactly the
    reference producer's data path (ProducerDeployment.yaml:90-95): endpoint +
    bucket + key env vars, credentials from the ``keysecret`` pair.
    ``faults``/``breaker`` guard the producer↔store edge
    (runtime/faults.py, runtime/breaker.py)."""
    from ccfd_tpu.store.client import S3Client
    from ccfd_tpu.store.objectstore import Credentials

    client = S3Client(
        cfg.s3_endpoint,
        Credentials(cfg.access_key_id, cfg.secret_access_key),
        faults=faults, breaker=breaker, tracer=tracer,
    )
    return load_csv_bytes(client.get(cfg.s3_bucket, cfg.filename), limit=limit)


class Producer:
    def __init__(
        self,
        cfg: Config,
        broker: Broker,
        dataset: Dataset | None = None,
        registry: Registry | None = None,
        store_faults=None,
        store_breaker=None,
        tracer=None,
    ):
        self.cfg = cfg
        self.broker = broker
        # observability/trace.py: each produced batch opens a ROOT span
        # ("producer.batch") whose context is stamped onto the records as
        # a traceparent header — the head of the end-to-end pipeline trace
        # the router/engine/notify resume downstream
        self.tracer = tracer
        if dataset is not None:
            self.dataset = dataset
        elif cfg.s3_endpoint:
            self.dataset = dataset_from_store(
                cfg, faults=store_faults, breaker=store_breaker,
                tracer=tracer)
        else:
            self.dataset = load_dataset()
        self.registry = registry or Registry()
        self._c_rows = self.registry.counter("producer_rows_total", "rows produced")

    def run(
        self,
        limit: int | None = None,
        rate_per_s: float | None = None,
        wire_format: str = "dict",
    ) -> int:
        """Stream rows to the tx topic; returns number produced.

        ``rate_per_s`` paces emission (sleep-based) for latency experiments;
        None streams as fast as the bus accepts (throughput experiments).
        ``wire_format="csv"`` emits raw CSV byte rows (the reference's
        creditcard.csv line format) which the router decodes through the
        native C++ fast path; ``"dict"`` emits parsed transactions.
        """
        if wire_format == "csv":
            X = self.dataset.X
            payloads = (
                (",".join(repr(float(v)) for v in X[i]).encode(), i)
                for i in range(X.shape[0])
            )
        else:
            payloads = ((tx, tx["id"]) for tx in iter_transactions(self.dataset))

        produced = 0
        interval = 1.0 / rate_per_s if rate_per_s else 0.0
        # unpaced + networked broker: chunk rows into one HTTP round-trip
        # per batch instead of one per row (RemoteBroker.produce_batch)
        batcher = getattr(self.broker, "produce_batch", None)
        if not interval and batcher is not None:
            chunk_v: list = []
            chunk_k: list = []
            for value, key in payloads:
                if limit is not None and produced + len(chunk_v) >= limit:
                    break
                chunk_v.append(value)
                chunk_k.append(key)
                if len(chunk_v) >= 1000:
                    produced += self._produce_chunk(batcher, chunk_v, chunk_k)
                    chunk_v, chunk_k = [], []
            if chunk_v:
                produced += self._produce_chunk(batcher, chunk_v, chunk_k)
            return produced
        next_emit = time.perf_counter()
        for value, key in payloads:
            if limit is not None and produced >= limit:
                break
            if interval:
                now = time.perf_counter()
                if now < next_emit:
                    time.sleep(next_emit - now)
                next_emit += interval
            # the reference's producer-side `topic` env var (ProducerDeployment
            # contract) decides the sink topic, not the router's KAFKA_TOPIC
            if self.tracer is not None:
                # paced emission is the latency experiment: a root span per
                # record keeps one-transaction traces attributable
                from ccfd_tpu.observability.trace import inject_headers

                with self.tracer.span("producer.produce"):
                    self.broker.produce(
                        self.cfg.producer_topic, value, key=key,
                        headers=inject_headers())
            else:
                self.broker.produce(self.cfg.producer_topic, value, key=key)
            self._c_rows.inc()
            produced += 1
        return produced

    def _produce_chunk(self, batcher, values: list, keys: list) -> int:
        """One batched produce, traced as one root span: the span context
        stamps every record of the batch (one shared headers dict)."""
        if self.tracer is None:
            n = batcher(self.cfg.producer_topic, values, keys)
            self._c_rows.inc(len(values))
            return n
        from ccfd_tpu.observability.trace import inject_headers

        with self.tracer.span("producer.batch",
                              attrs={"rows": len(values)}):
            n = batcher(self.cfg.producer_topic, values, keys,
                        headers=inject_headers())
        self._c_rows.inc(len(values))
        return n
