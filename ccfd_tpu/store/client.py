"""Object-store client: what the reference's producer + aws-cli do.

The reference producer receives ``s3endpoint``/``s3bucket``/``filename`` and
``ACCESS_KEY_ID``/``SECRET_ACCESS_KEY`` (from the ``keysecret`` secret) and
pulls ``creditcard.csv`` over S3 (reference
deploy/kafka/ProducerDeployment.yaml:77-97, deploy/ceph/s3-secretceph.yaml).
``S3Client`` reproduces that consumer side against either the HTTP store
server (v2-signed requests over urllib) or an ``inproc://`` store in the
same process, chosen by the endpoint scheme — the same dual-transport seam
the bus uses.
"""

from __future__ import annotations

import email.utils
import urllib.error
import urllib.request
import xml.etree.ElementTree as ET

from ccfd_tpu.store.objectstore import (
    AccessDenied,
    Credentials,
    NoSuchKey,
    ObjectStore,
    resolve_inproc,
)
from ccfd_tpu.store.server import quote_key, sign_v2


class S3Client:
    def __init__(self, endpoint: str, creds: Credentials, timeout_s: float = 10.0,
                 breaker=None, faults=None, tracer=None):
        self.endpoint = endpoint.rstrip("/")
        self.creds = creds
        self.timeout_s = timeout_s
        # producer↔store resilience edge (runtime/breaker.py,
        # runtime/faults.py): gates both transports — the producer's retry
        # loop sees CircuitOpenError/InjectedFault as ordinary
        # ConnectionErrors. tracer: each store op is an rpc.store client
        # span; the HTTP transport carries traceparent.
        self._breaker = breaker
        self._faults = faults
        self._tracer = tracer
        self._inproc: ObjectStore | None = None
        if endpoint.startswith("inproc://"):
            self._inproc = resolve_inproc(endpoint)
            self._inproc.check_access(creds.access_key)
            if self._inproc.secret_for(creds.access_key) != creds.secret_key:
                raise AccessDenied("secret key mismatch")

    # --- HTTP plumbing ---------------------------------------------------
    def _request(self, method: str, path: str, data: bytes | None = None) -> bytes:
        return self._call(self._request_raw, method, path, data)

    def _call(self, fn, *args):
        if self._tracer is not None:
            with self._tracer.span("rpc.store"):
                return self._call_untraced(fn, *args)
        return self._call_untraced(fn, *args)

    def _call_untraced(self, fn, *args):
        if self._breaker is not None or self._faults is not None:
            return self._guarded(fn, *args)
        return fn(*args)

    def _guarded(self, fn, *args):
        """Breaker gate + outcome recording + fault perturbation around one
        store call (shared by the HTTP and inproc transports)."""
        import time as _time

        if self._breaker is not None and not self._breaker.allow():
            from ccfd_tpu.runtime.breaker import CircuitOpenError

            raise CircuitOpenError("circuit open for the object store")
        t0 = _time.monotonic()
        try:
            corrupt = (self._faults.before()
                       if self._faults is not None else False)
            out = fn(*args)
            if self._faults is not None:
                out = self._faults.after(out, corrupt)
        except (NoSuchKey, AccessDenied):
            # application-level outcomes over a HEALTHY transport: record
            # success — a gated call that records nothing would leak its
            # HALF_OPEN probe slot and wedge the circuit
            if self._breaker is not None:
                self._breaker.record_success(_time.monotonic() - t0)
            raise
        except Exception:
            if self._breaker is not None:
                self._breaker.record_failure(_time.monotonic() - t0)
            raise
        if self._breaker is not None:
            self._breaker.record_success(_time.monotonic() - t0)
        return out

    def _request_raw(self, method: str, path: str, data: bytes | None = None) -> bytes:
        headers = {"Date": email.utils.formatdate(usegmt=True)}
        if self._tracer is not None:
            from ccfd_tpu.observability.trace import inject_headers

            inject_headers(headers)  # traceparent is not part of the
            # v2 StringToSign set, so signing stays valid
        if data is not None:
            # set explicitly so the signed Content-Type matches what urllib
            # sends (it would otherwise inject x-www-form-urlencoded unsigned)
            headers["Content-Type"] = "application/octet-stream"
        sig = sign_v2(self.creds.secret_key, method, path.split("?")[0], headers)
        headers["Authorization"] = f"AWS {self.creds.access_key}:{sig}"
        req = urllib.request.Request(
            self.endpoint + path, data=data, headers=headers, method=method
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            body = e.read().decode("utf-8", "replace")
            if e.code == 403:
                raise AccessDenied(body) from None
            if e.code == 404:
                raise NoSuchKey(body) from None
            raise

    # --- API -------------------------------------------------------------
    def create_bucket(self, bucket: str) -> None:
        if self._inproc is not None:
            self._call(self._inproc.create_bucket, bucket)
        else:
            self._request("PUT", f"/{bucket}")

    def put(self, bucket: str, key: str, data: bytes) -> None:
        if self._inproc is not None:
            self._call(self._inproc.put, bucket, key, data)
        else:
            self._request("PUT", f"/{bucket}/{quote_key(key)}", data=data)

    def get(self, bucket: str, key: str) -> bytes:
        if self._inproc is not None:
            return self._call(self._inproc.get, bucket, key)
        return self._request("GET", f"/{bucket}/{quote_key(key)}")

    def delete(self, bucket: str, key: str) -> None:
        if self._inproc is not None:
            self._call(self._inproc.delete, bucket, key)
        else:
            self._request("DELETE", f"/{bucket}/{quote_key(key)}")

    def list(self, bucket: str, prefix: str = "") -> list[str]:
        """Object keys, the `aws s3 ls` check (reference README.md:320-343)."""
        if self._inproc is not None:
            return [o.key for o in
                    self._call(self._inproc.list, bucket, prefix)]
        body = self._request("GET", f"/{bucket}?prefix={quote_key(prefix)}")
        root = ET.fromstring(body)
        return [c.findtext("Key", "") for c in root.iter("Contents")]
