"""S3-shaped object store: the dataset layer (reference L0).

The reference keeps ``creditcard.csv`` in a Rook-Ceph S3 object store and
hands the producer an endpoint + bucket + key plus credentials from the
``keysecret`` secret (reference deploy/ceph/s3-secretceph.yaml:1-8,
deploy/kafka/ProducerDeployment.yaml:77-97, setup README.md:136-343). This
module reproduces that capability locally: named buckets of keyed byte
objects with access-key/secret-key authentication, backed either by memory
(tests, demo) or a filesystem root (durable). The HTTP face lives in
``ccfd_tpu/store/server.py`` (S3 v2-signed REST subset) and the consumer
side in ``ccfd_tpu/store/client.py``.

Auth model matches the reference secret contract: a store is provisioned
with (access_key, secret_key) pairs; every operation presents an access key
that must be known. Signature verification happens at the HTTP layer (the
in-process path trusts the caller the way the producer pod trusts its
mounted secret).
"""

from __future__ import annotations

import hashlib
import os
import re
import threading
import time
from dataclasses import dataclass

_BUCKET_RE = re.compile(r"^[a-z0-9][a-z0-9.-]{2,62}$")


@dataclass(frozen=True)
class Credentials:
    """The reference's ``keysecret`` pair (s3-secretceph.yaml:4-7)."""

    access_key: str
    secret_key: str


@dataclass(frozen=True)
class ObjectInfo:
    key: str
    size: int
    etag: str
    last_modified: float


class StoreError(Exception):
    status = 500


class NoSuchBucket(StoreError):
    status = 404


class NoSuchKey(StoreError):
    status = 404


class AccessDenied(StoreError):
    status = 403


class InvalidBucketName(StoreError):
    status = 400


class ObjectStore:
    """Bucket/key byte store with optional filesystem persistence.

    ``root=None`` keeps everything in memory. With a ``root`` directory,
    buckets are subdirectories and keys are files (slashes in keys become
    nested paths), so a store survives process restarts the way the
    reference's Ceph PVs do.
    """

    def __init__(self, root: str | None = None):
        self._root = root
        self._lock = threading.RLock()
        self._mem: dict[str, dict[str, tuple[bytes, float]]] = {}
        self._creds: dict[str, str] = {}
        if root:
            os.makedirs(root, exist_ok=True)
            for name in sorted(os.listdir(root)):
                if os.path.isdir(os.path.join(root, name)):
                    self._mem.setdefault(name, {})

    # --- credentials -----------------------------------------------------
    def add_credentials(self, creds: Credentials) -> None:
        with self._lock:
            self._creds[creds.access_key] = creds.secret_key

    def secret_for(self, access_key: str) -> str:
        with self._lock:
            try:
                return self._creds[access_key]
            except KeyError:
                raise AccessDenied(f"unknown access key {access_key!r}") from None

    def check_access(self, access_key: str) -> None:
        self.secret_for(access_key)

    # --- buckets ---------------------------------------------------------
    def create_bucket(self, bucket: str) -> None:
        if not _BUCKET_RE.match(bucket):
            raise InvalidBucketName(bucket)
        with self._lock:
            self._mem.setdefault(bucket, {})
            if self._root:
                os.makedirs(os.path.join(self._root, bucket), exist_ok=True)

    def list_buckets(self) -> list[str]:
        with self._lock:
            return sorted(self._mem)

    def _bucket(self, bucket: str) -> dict[str, tuple[bytes, float]]:
        try:
            return self._mem[bucket]
        except KeyError:
            raise NoSuchBucket(bucket) from None

    # --- objects ---------------------------------------------------------
    def _path(self, bucket: str, key: str) -> str:
        assert self._root
        broot = os.path.join(self._root, bucket)
        p = os.path.normpath(os.path.join(broot, key))
        if p != broot and not p.startswith(broot + os.sep):
            raise AccessDenied(f"key escapes bucket: {key!r}")
        return p

    def put(self, bucket: str, key: str, data: bytes) -> ObjectInfo:
        data = bytes(data)
        now = time.time()
        with self._lock:
            b = self._bucket(bucket)
            b[key] = (data, now)
            if self._root:
                # the shared atomic-write helper (tmp + fsync + rename,
                # runtime/durability.py): NO frame — object bytes are the
                # caller's payload, integrity rides the etag
                from ccfd_tpu.runtime.durability import atomic_write_bytes

                p = self._path(bucket, key)
                atomic_write_bytes(p, data, artifact="object")
        return ObjectInfo(key, len(data), _etag(data), now)

    def get(self, bucket: str, key: str) -> bytes:
        with self._lock:
            b = self._bucket(bucket)
            if key in b:
                return b[key][0]
            if self._root:
                p = self._path(bucket, key)
                if os.path.exists(p):
                    with open(p, "rb") as f:
                        data = f.read()
                    b[key] = (data, os.path.getmtime(p))
                    return data
            raise NoSuchKey(f"{bucket}/{key}")

    def head(self, bucket: str, key: str) -> ObjectInfo:
        data = self.get(bucket, key)
        with self._lock:
            mtime = self._bucket(bucket)[key][1]
        return ObjectInfo(key, len(data), _etag(data), mtime)

    def delete(self, bucket: str, key: str) -> None:
        with self._lock:
            b = self._bucket(bucket)
            b.pop(key, None)
            if self._root:
                p = self._path(bucket, key)
                if os.path.exists(p):
                    os.remove(p)

    def list(self, bucket: str, prefix: str = "") -> list[ObjectInfo]:
        """`aws s3 ls`-equivalent listing (reference README.md:320-343).

        Filesystem-backed objects are stat'ed, not read: listing a bucket of
        large CSVs must not pull their bytes into memory (etag of uncached
        files is computed from size+mtime, a weak but read-free identity).
        """
        with self._lock:
            b = self._bucket(bucket)
            out = {
                k: ObjectInfo(k, len(v), _etag(v), ts)
                for k, (v, ts) in b.items()
                if k.startswith(prefix)
            }
            if self._root:
                broot = os.path.join(self._root, bucket)
                if os.path.isdir(broot):
                    for dirpath, _, files in os.walk(broot):
                        for fn in files:
                            p = os.path.join(dirpath, fn)
                            k = os.path.relpath(p, broot)
                            if k not in out and k.startswith(prefix):
                                st = os.stat(p)
                                weak = hashlib.md5(
                                    f"{st.st_size}:{st.st_mtime_ns}".encode()
                                ).hexdigest()
                                out[k] = ObjectInfo(k, st.st_size, weak, st.st_mtime)
        return sorted(out.values(), key=lambda o: o.key)


def _etag(data: bytes) -> str:
    return hashlib.md5(data).hexdigest()


# --- inproc endpoint registry (mirrors the bus's inproc:// seam) ---------
_INPROC: dict[str, ObjectStore] = {}
_INPROC_LOCK = threading.Lock()


def register_inproc(name: str, store: ObjectStore) -> str:
    """Bind a store to an ``inproc://<name>`` endpoint for same-process use."""
    with _INPROC_LOCK:
        _INPROC[name] = store
    return f"inproc://{name}"


def resolve_inproc(endpoint: str) -> ObjectStore:
    name = endpoint[len("inproc://"):]
    with _INPROC_LOCK:
        try:
            return _INPROC[name]
        except KeyError:
            raise NoSuchBucket(f"no inproc store {name!r}") from None


