"""Online retraining loop: process-engine labels -> sharded SGD -> hot swap.

BASELINE.json configs[4]: "Online retrain from jBPM human-task labels (SGD
on TPU, pmap over v5e-4)". The loop:

1. consume label events from the bus (published by the fraud process on
   resolution — ccfd_tpu/process/fraud.py ``record``),
2. accumulate a replay buffer; once ``retrain_min_labels`` are available,
   run train steps on ``retrain_batch``-row batches through the
   mesh-sharded train step (ccfd_tpu/parallel/train.make_train_step),
3. hand the candidate to the model-lifecycle controller
   (ccfd_tpu/lifecycle/controller.py) for shadow -> canary -> gated
   promotion — or, in the legacy opt-in direct-swap mode (``lifecycle``
   unset), publish it straight into the serving scorer with
   ``Scorer.swap_params`` — double-buffered, serving never pauses.

Labels are rare relative to traffic (only resolved fraud processes emit
them), so the buffer is a reservoir over the last ``buffer_size`` labels
and every retrain epoch resamples from it. Sampling uses a seeded,
injectable RNG that ``reset()`` re-seeds, so a supervisor respawn (or a
re-run with the same label stream) reproduces the same candidates —
the determinism the lifecycle's audit trail and tests depend on.
"""

from __future__ import annotations

import threading
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ccfd_tpu.bus.broker import Broker
from ccfd_tpu.config import Config
from ccfd_tpu.data.ccfd import FEATURE_NAMES
from ccfd_tpu.metrics.prom import Registry
from ccfd_tpu.parallel.checkpoint import CheckpointManager
from ccfd_tpu.parallel.train import TrainConfig, init_state, make_train_step
from ccfd_tpu.serving.scorer import Scorer


class OnlineTrainer:
    def __init__(
        self,
        cfg: Config,
        broker: Broker,
        scorer: Scorer,
        params: Any,
        tc: TrainConfig | None = None,
        mesh=None,
        registry: Registry | None = None,
        checkpoints: CheckpointManager | None = None,
        buffer_size: int = 65536,
        steps_per_round: int = 8,
        seed: int = 0,
        rng: np.random.Generator | None = None,
        lifecycle: Any = None,
        partitioner: Any = None,
    ):
        self.cfg = cfg
        self.broker = broker
        self.scorer = scorer
        self.tc = tc or TrainConfig()
        self.mesh = mesh
        # partitioning layer (parallel/partition.py): the train step jits
        # with explicit shardings and DONATED sharded state; batch sizes
        # round to data-axis multiples so every shard sees a static shape
        self.partitioner = partitioner
        self.registry = registry or Registry()
        self.checkpoints = checkpoints
        self.buffer_size = buffer_size
        self.steps_per_round = steps_per_round
        # governed rollout (lifecycle/controller.py): when set, candidates
        # go through shadow -> canary -> gated promotion instead of the
        # legacy direct swap (kept for lifecycle=None callers)
        self.lifecycle = lifecycle
        self.seed = seed
        # batch sampling must be reproducible across runs: an injected rng
        # is the caller's contract; the default is seeded here AND
        # re-seeded by reset() so a supervisor respawn replays the same
        # sampling stream instead of continuing from opaque state
        self._rng_injected = rng is not None
        self._rng = rng if rng is not None else np.random.default_rng(seed)
        self.labels_seen = 0  # lifetime label count: the lineage watermark

        self._consumer = broker.consumer("online-trainer", (cfg.labels_topic,))
        self._X = np.zeros((0, len(FEATURE_NAMES)), np.float32)
        self._y = np.zeros((0,), np.float32)
        # fresh buffers: the train step donates its state, so it must never
        # alias the pytree the serving scorer holds
        self._state = init_state(jax.tree.map(lambda a: jnp.array(a, copy=True), params), self.tc)
        self._new_labels = 0
        # lifecycle rebase request (controller thread -> trainer thread):
        # applied at the top of the next step(), never mid-train
        self._rebase_params: Any = None
        self._step_fn = make_train_step(self.tc, mesh=mesh,
                                        partitioner=partitioner)
        self._stop = threading.Event()

        r = self.registry
        self._c_labels = r.counter("retrain_labels_total", "labels consumed by class")
        self._c_steps = r.counter("retrain_steps_total", "optimizer steps run")
        self._c_swaps = r.counter("retrain_param_swaps_total", "serving hot swaps")
        self._g_loss = r.gauge("retrain_last_loss", "loss of last retrain step")

    # -- label ingestion ---------------------------------------------------
    def _ingest(self, max_records: int = 4096) -> int:
        records = self._consumer.poll(max_records, 0.0)
        if not records:
            return 0
        rows, labels = [], []
        for rec in records:
            msg = rec.value or {}
            tx = msg.get("transaction") or {}
            try:  # parse the full record before appending anything: a partial
                # failure must not desynchronize the (X, y) pairing
                row = [float(tx.get(n, 0.0) or 0.0) for n in FEATURE_NAMES]
                label = float(msg.get("label", 0))
            except (TypeError, ValueError):
                continue
            rows.append(row)
            labels.append(label)
            self._c_labels.inc(
                labels={"class": "fraud" if label > 0.5 else "legit"}
            )
        if not rows:
            return 0
        self._X = np.concatenate([self._X, np.asarray(rows, np.float32)])[
            -self.buffer_size :
        ]
        self._y = np.concatenate([self._y, np.asarray(labels, np.float32)])[
            -self.buffer_size :
        ]
        self.labels_seen += len(rows)
        return len(rows)

    # -- lifecycle rebase --------------------------------------------------
    def rebase(self, params: Any) -> None:
        """Re-base the training state onto ``params`` (the champion).

        Wired by the operator as the lifecycle controller's rebase hook:
        after a candidate is REJECTED or ROLLED BACK, continuing to train
        from its weights would make every later candidate descend from
        the discarded model while the lineage records parent=champion.
        Thread-safe hand-off: the request is staged here (any thread) and
        applied at the next step() boundary on the trainer thread — never
        mid-train-step, whose donated buffers must not race a swap."""
        self._rebase_params = jax.tree.map(
            lambda a: jnp.array(np.asarray(a)), params)

    # -- one retrain round -------------------------------------------------
    def step(self) -> bool:
        """Ingest labels; train + swap only when NEW labels arrived and the
        buffer is warm. Returns whether a swap happened (so the run loop
        sleeps instead of re-training a stale buffer in a tight loop)."""
        pending = self._rebase_params
        if pending is not None:
            self._rebase_params = None
            self._state = init_state(pending, self.tc)
        self._new_labels += self._ingest()
        if len(self._y) < self.cfg.retrain_min_labels or self._new_labels == 0:
            return False
        self._new_labels = 0
        batch = min(self.cfg.retrain_batch, len(self._y))
        if self.partitioner is not None:
            # static shard shapes: the batch must split evenly over the
            # data axis (sampling with replacement, so rounding UP to the
            # axis size is always satisfiable)
            batch = self.partitioner.round_batch(batch)
        loss = None
        for _ in range(self.steps_per_round):
            idx = self._rng.integers(0, len(self._y), size=batch)
            x = jnp.asarray(self._X[idx])
            y = jnp.asarray(self._y[idx])
            self._state, loss = self._step_fn(self._state, x, y)
            self._c_steps.inc()
        if loss is not None:
            self._g_loss.set(float(loss))
        new_params = self._state["params"]
        if self.lifecycle is not None:
            # governed rollout: the controller checkpoints/versions the
            # candidate and walks it through shadow/canary before any
            # params reach serving (lifecycle/controller.py)
            self.lifecycle.submit_candidate(
                new_params, label_watermark=self.labels_seen)
        else:
            self.scorer.swap_params(new_params)
            self._c_swaps.inc()
        if self.checkpoints is not None:
            self.checkpoints.save(int(self._state["step"]), new_params)
        return True

    # -- daemon ------------------------------------------------------------
    def reset(self) -> None:
        """Re-arm after stop(); called by the supervisor before respawn
        (clearing inside run() would race a concurrent stop()). Re-seeds
        the default RNG so the respawned loop's batch sampling replays the
        same stream (an injected rng is the caller's to manage)."""
        self._stop.clear()
        if not self._rng_injected:
            self._rng = np.random.default_rng(self.seed)

    def run(self, interval_s: float = 1.0) -> None:
        while not self._stop.is_set():
            if not self.step():
                self._stop.wait(interval_s)

    def start(self, interval_s: float = 1.0) -> threading.Thread:
        self.reset()
        t = threading.Thread(
            target=self.run, args=(interval_s,), daemon=True, name="ccfd-retrain"
        )
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()

    def close(self) -> None:
        self.stop()
        self._consumer.close()
