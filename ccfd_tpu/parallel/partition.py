"""First-class partitioning layer: named mesh, regex rules, partitioners.

The multichip dryrun (``__graft_entry__.dryrun_multichip``) proved sharded
serving/retrain compiles and answers on 8 devices, but every sharding
decision lived ad hoc at its call site — the Scorer hand-rolled its batch
NamedSharding, the train step hand-rolled ``mlp_param_spec``, and nothing
owned the questions the LIVE platform has to answer: which axis does a
param shard over, how do host trees get on and off the mesh, and how does
a hot swap publish sharded params under in-flight SPMD dispatches.

This module is that owner (ROADMAP item 2; SNIPPETS.md [1]-[3]):

- :func:`match_partition_rules` — regex rules over ``/``-joined param
  pytree paths -> a pytree of ``PartitionSpec``. Scalars and size-1
  leaves never partition; a param no rule covers raises (an unsharded
  wide layer silently replicating is exactly the OOM-later bug the rule
  table exists to catch).
- :class:`SpecLayout` — the canonical ``data``/``fsdp``/``tp`` spec
  vocabulary plus the stock rule tables for the model families
  (:func:`mlp_rules`, :func:`seq_rules`).
- :class:`DataParallelPartitioner` / :class:`SPMDPartitioner` — shard /
  gather fns over a named mesh, explicit-sharding entry points for the
  donated train step, and the **publish path**: a param swap takes the
  ParallelRouter's group pause barrier so no worker's in-flight sharded
  dispatch interleaves with the re-layout (:class:`PublishGate`, armed
  via ``set_barrier`` and entered by the scorers' ``swap_params``).
- :func:`params_fingerprint` — sha256 over the FULLY-GATHERED leaf bytes
  (path-sorted, dtype+shape framed), so a checkpoint lineage hash is
  identical whether the params lived on 1 chip or 8 (device-count-
  invariant provenance; lifecycle/versions.py records it).

Everything drills on CPU CI under
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` exactly like the
dryrun.
"""

from __future__ import annotations

import hashlib
import re
import threading
from typing import Any, Callable, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ccfd_tpu.parallel.mesh import DATA_AXIS, FSDP_AXIS, TP_AXIS


# -- pytree path naming ------------------------------------------------------

def _path_str(path: Any) -> str:
    """``/``-joined human path for one pytree leaf (dict keys, sequence
    indices, dataclass fields)."""
    parts: list[str] = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:  # pragma: no cover - exotic path entry
            parts.append(str(p))
    return "/".join(parts)


def named_tree_map(fn: Callable[[str, Any], Any], tree: Any) -> Any:
    """``jax.tree.map`` with the leaf's ``/``-joined path as first arg."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: fn(_path_str(path), leaf), tree
    )


def tree_paths(tree: Any) -> list[str]:
    """Every leaf path in ``tree``, ``/``-joined (rule-table authoring aid)."""
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [_path_str(path) for path, _ in leaves]


# -- regex partition rules ---------------------------------------------------

def match_partition_rules(
    rules: Sequence[tuple[str, P]], params: Any
) -> Any:
    """Pytree of ``PartitionSpec`` from ``(regex, spec)`` rules.

    Scalars and single-element leaves always replicate (``P()``) without
    consulting the rules — partitioning a step counter or a 1-element
    bias is never meaningful. First matching rule wins (``re.search``
    over the ``/``-joined path). A leaf NO rule covers raises: silence
    here would hand a caller who needed the sharded layout a replicated
    tree and an OOM later. Works over optimizer-state trees too — optax
    states embed param-structured subtrees whose leaf paths end with the
    same param names, so the same table covers them.
    """

    def spec_for(name: str, leaf: Any) -> P:
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()
        for rule, spec in rules:
            if re.search(rule, name) is not None:
                return spec
        raise ValueError(f"partition rule not found for param: {name!r}")

    return named_tree_map(spec_for, params)


class SpecLayout:
    """Canonical PartitionSpecs aligned with the named mesh axes.

    One place spells how each tensor role lays out over
    ``data``/``fsdp``/``tp``; the per-family rule tables below only bind
    regexes to these roles. Axis names are parameters so the same layout
    drives the legacy 2-D ``(data, model)`` mesh (``tp_axis="model"``).
    """

    def __init__(self, data_axis: str = DATA_AXIS,
                 fsdp_axis: str = FSDP_AXIS, tp_axis: str = TP_AXIS):
        self.data_axis = data_axis
        self.fsdp_axis = fsdp_axis
        self.tp_axis = tp_axis

    def batch(self) -> P:
        """Row batches shard over data; feature dim stays whole."""
        return P(self.data_axis, None)

    def rows(self) -> P:
        """Per-row outputs (probabilities/labels) shard over data."""
        return P(self.data_axis)

    def replicated(self) -> P:
        return P()

    def col_parallel(self) -> P:
        """(in, out) weight, column-sharded: activations come out sharded
        on the hidden dim, no collective needed going in."""
        return P(self.fsdp_axis, self.tp_axis)

    def row_parallel(self) -> P:
        """(in, out) weight, row-sharded: each chip contracts its hidden
        slice; XLA inserts the psum."""
        return P(self.tp_axis, None)

    def hidden_bias(self) -> P:
        """Bias on a tp-sharded hidden dim follows its activations."""
        return P(self.tp_axis)


def mlp_rules(layout: SpecLayout | None = None) -> list[tuple[str, P]]:
    """Megatron layout for the flagship MLP (models/mlp.py tree:
    ``norm/{mu,sigma}`` + ``layers/<i>/{w,b}``) — the same layout
    ``sharding.mlp_param_spec`` hand-writes, expressed as rules (parity
    is test-pinned)."""
    lo = layout or SpecLayout()
    return [
        (r"norm/", lo.replicated()),
        # first layer: column-parallel in; its bias rides the sharded
        # hidden dim
        (r"layers/0/w", P(None, lo.tp_axis)),
        (r"layers/0/b", lo.hidden_bias()),
        # last layer: row-parallel out (psum produces replicated logits);
        # the matching is ordered, so the generic hidden rule below only
        # sees the middle layers
        (r"layers/\d+/w$", lo.row_parallel()),
        (r"layers/\d+/b$", lo.replicated()),
    ]


def seq_rules(layout: SpecLayout | None = None) -> list[tuple[str, P]]:
    """Transformer layout for the history model (models/seq.py tree:
    embed / blocks/<i>/{ln1,qkv,proj,ln2,mlp_in,mlp_out} / head):
    attention + MLP matmuls shard fsdp x tp, norms/bias replicate."""
    lo = layout or SpecLayout()
    return [
        (r"embed/w", P(None, lo.tp_axis)),
        (r"embed/b", lo.hidden_bias()),
        (r"blocks/\d+/(qkv|mlp_in)/w", lo.col_parallel()),
        (r"blocks/\d+/(proj|mlp_out)/w", lo.row_parallel()),
        (r"blocks/\d+/.*/(b|scale|bias)", lo.replicated()),
        (r"head/", lo.replicated()),
        (r"norm/", lo.replicated()),
    ]


# -- shard / gather ----------------------------------------------------------

def make_shard_and_gather_fns(
    mesh: Mesh, partition_specs: Any
) -> tuple[Any, Any]:
    """Pytrees of per-leaf shard (host -> mesh) and gather (mesh -> host
    numpy) callables from a pytree of PartitionSpecs.

    Gather is a plain ``np.asarray``: every serving mesh here is fully
    addressable (one process), so the conversion materializes the global
    array — giving byte-identical host trees regardless of device count
    (what :func:`params_fingerprint` relies on)."""

    def make_shard(spec: P):
        sh = NamedSharding(mesh, spec)
        return lambda leaf: jax.device_put(leaf, sh)

    def make_gather(_spec: P):
        return lambda leaf: np.asarray(leaf)

    shard_fns = jax.tree.map(make_shard, partition_specs,
                             is_leaf=lambda x: isinstance(x, P))
    gather_fns = jax.tree.map(make_gather, partition_specs,
                              is_leaf=lambda x: isinstance(x, P))
    return shard_fns, gather_fns


def gather_params(params: Any) -> Any:
    """Fully-gathered host copy of a (possibly sharded) param tree.
    Floating dtypes are preserved — this is the byte-identity surface
    checkpoints and fingerprints read."""
    return jax.tree.map(lambda a: np.asarray(a), params)


def params_fingerprint(params: Any) -> str:
    """sha256 hex over the fully-gathered param bytes.

    Leaves hash in sorted-path order, each framed with its path, dtype
    and shape, so the digest is invariant to device count and sharding
    layout but NOT to a renamed/reshaped/retyped leaf. This is the
    checkpoint-lineage hash (lifecycle/versions.py): the same champion
    restored on a 1-chip laptop and an 8-chip mesh must audit as the
    same bytes."""
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    h = hashlib.sha256()
    for path, leaf in sorted(leaves, key=lambda pl: _path_str(pl[0])):
        a = np.asarray(leaf)
        h.update(_path_str(path).encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


# -- publish barrier ---------------------------------------------------------

class PublishGate:
    """Context manager a sharded scorer's ``swap_params`` enters: pauses
    the router pool (the existing group-wide batch-boundary barrier) for
    the duration of the publish, so no worker's in-flight sharded
    dispatch interleaves with the param re-layout.

    ``barrier`` is anything with ``pause(timeout_s) -> bool`` /
    ``resume()`` (Router and ParallelRouter both). A pause that times out
    (e.g. a wedged dispatch the watchdog is about to kill) does NOT block
    the publish — the scorer's double buffering keeps an interleaved swap
    safe, the barrier is what makes it *quiescent*; the timeout keeps a
    sick pool from deadlocking a rollback. The hold is ALWAYS released on
    exit once a pause was requested, ack or no ack — ``pause()`` takes
    its holders before awaiting acks, and an un-resumed hold would park
    every worker at its next batch boundary forever (the same
    resume-in-finally contract runtime/recovery.py keeps). Re-entrant so
    a respawn that swaps inside an outer publish doesn't self-deadlock."""

    def __init__(self, barrier: Any, timeout_s: float = 10.0,
                 c_publishes: Any = None, c_timeouts: Any = None):
        self.barrier = barrier
        self.timeout_s = float(timeout_s)
        self._local = threading.local()
        self.publishes = 0
        self.pause_timeouts = 0
        # optional prom counters (the operator passes its mesh registry's)
        self._c_publishes = c_publishes
        self._c_timeouts = c_timeouts

    def __enter__(self) -> "PublishGate":
        depth = getattr(self._local, "depth", 0)
        self._local.depth = depth + 1
        self._local.requested = getattr(self._local, "requested", False)
        if depth == 0:
            self.publishes += 1
            if self._c_publishes is not None:
                self._c_publishes.inc()
            acked = False
            self._local.requested = True
            try:
                acked = bool(self.barrier.pause(self.timeout_s))
            except Exception:  # noqa: BLE001 - a dead pool must not block
                pass  # the publish (resume() on exit is defensive)
            if not acked:
                self.pause_timeouts += 1
                if self._c_timeouts is not None:
                    self._c_timeouts.inc()
        return self

    def __exit__(self, *exc: Any) -> None:
        self._local.depth = depth = self._local.depth - 1
        if depth == 0 and self._local.requested:
            # release the hold even when the ack never arrived: pause()
            # takes its holders BEFORE awaiting acks, and a leaked hold
            # parks every worker at its next batch boundary forever
            self._local.requested = False
            try:
                self.barrier.resume()
            except Exception:  # noqa: BLE001
                pass


# -- partitioners ------------------------------------------------------------

class Partitioner:
    """Shared surface: mesh + layout + shard/gather + the publish path.

    Subclasses decide the PARAM layout; batches always shard over the
    data axis and per-row outputs come back data-sharded (never gathered
    onto one chip before D2H)."""

    def __init__(self, mesh: Mesh, data_axis: str = DATA_AXIS,
                 layout: SpecLayout | None = None):
        if data_axis not in mesh.axis_names:
            raise ValueError(
                f"mesh {mesh.axis_names} has no axis {data_axis!r}")
        self.mesh = mesh
        self.data_axis = data_axis
        self.layout = layout or SpecLayout(data_axis=data_axis)
        self.batch_sharding = NamedSharding(mesh, self.layout.batch())
        self.out_sharding = NamedSharding(mesh, self.layout.rows())
        self.replicated = NamedSharding(mesh, P())
        # swap-vs-dispatch barrier: armed by the operator once the router
        # pool exists (set_barrier); None = publish without quiescing
        self.gate: PublishGate | None = None

    # - layout ---------------------------------------------------------------
    @property
    def data_size(self) -> int:
        return int(self.mesh.shape[self.data_axis])

    @property
    def n_devices(self) -> int:
        return int(self.mesh.size)

    def round_batch(self, b: int) -> int:
        """Smallest multiple of the data-axis size covering ``b`` — every
        bucket must split evenly over the data axis."""
        d = self.data_size
        return -(-int(b) // d) * d

    def param_specs(self, params: Any) -> Any:
        raise NotImplementedError

    def param_sharding(self, params: Any) -> Any:
        return jax.tree.map(
            lambda spec: NamedSharding(self.mesh, spec),
            self.param_specs(params),
            is_leaf=lambda x: isinstance(x, P))

    # - shard / gather -------------------------------------------------------
    def shard_params(self, params: Any) -> Any:
        shard_fns, _ = make_shard_and_gather_fns(
            self.mesh, self.param_specs(params))
        return jax.tree.map(lambda fn, leaf: fn(leaf), shard_fns, params)

    def gather(self, params: Any) -> Any:
        return gather_params(params)

    def shard_batch(self, batch: Any) -> jax.Array:
        return jax.device_put(batch, self.batch_sharding)

    # - jit entry points -----------------------------------------------------
    def train_state_specs(self, state: Any) -> Any:
        """Shardings for an ``init_state``-shaped {params, opt_state,
        step} tree: params per the subclass layout, optimizer momentum
        sharded like its params, counters replicated."""
        pspec = self.param_specs(state["params"])
        ptree = jax.tree.structure(state["params"])

        def is_param_like(node: Any) -> bool:
            try:
                return jax.tree.structure(node) == ptree
            except TypeError:  # pragma: no cover
                return False

        opt = jax.tree.map(
            lambda node: pspec if is_param_like(node) else P(),
            state["opt_state"], is_leaf=is_param_like)
        return {"params": pspec, "opt_state": opt, "step": P()}

    def train_state_sharding(self, state: Any) -> Any:
        return jax.tree.map(
            lambda spec: NamedSharding(self.mesh, spec),
            self.train_state_specs(state),
            is_leaf=lambda x: isinstance(x, P))

    def partition_train_step(
        self, step: Callable[..., Any], state: Any
    ) -> Callable[..., Any]:
        """Jit the ``(state, x, y) -> (state, loss)`` step with explicit
        shardings and DONATED state buffers — the whole step is one SPMD
        executable, state never round-trips through host."""
        sh = self.train_state_sharding(state)
        return jax.jit(
            step,
            in_shardings=(sh, self.batch_sharding, self.out_sharding),
            out_shardings=(sh, self.replicated),
            donate_argnums=(0,),
        )

    # - publish path ---------------------------------------------------------
    def set_barrier(self, barrier: Any, timeout_s: float = 10.0,
                    registry: Any = None) -> None:
        """Arm the swap-vs-dispatch barrier (the router pool's group
        pause). Idempotent re-arming follows the newest pool (crash
        recovery swaps router incarnations). With a ``registry`` the
        gate's publish/timeout tallies also export as prom counters
        (the Device board's Mesh row)."""
        if barrier is None:
            self.gate = None
            return
        c_pub = c_to = None
        if registry is not None:
            c_pub = registry.counter(
                "ccfd_mesh_publishes_total",
                "sharded param publishes through the pause-barrier gate")
            c_to = registry.counter(
                "ccfd_mesh_publish_pause_timeouts_total",
                "publishes whose router-pool pause timed out (published "
                "anyway under double buffering; the pool was not "
                "quiescent)")
        self.gate = PublishGate(barrier, timeout_s,
                                c_publishes=c_pub, c_timeouts=c_to)


class DataParallelPartitioner(Partitioner):
    """Pure data parallelism: params replicate, batches shard over
    ``data``. The serving default — for the tabular CCFD models the data
    axis does nearly all the work (the reference's "more replicas"
    scaling, one SPMD program instead of N processes)."""

    def param_specs(self, params: Any) -> Any:
        return jax.tree.map(lambda _: P(), params)


class SPMDPartitioner(Partitioner):
    """Rule-driven SPMD: params shard per a regex rule table
    (:func:`match_partition_rules`), batches over ``data``. The wide-
    model escape hatch — fsdp/tp columns per the :class:`SpecLayout`
    vocabulary; XLA's partitioner chooses the collective schedule."""

    def __init__(self, mesh: Mesh, rules: Sequence[tuple[str, P]],
                 data_axis: str = DATA_AXIS,
                 layout: SpecLayout | None = None):
        super().__init__(mesh, data_axis=data_axis, layout=layout)
        self.rules = list(rules)

    def param_specs(self, params: Any) -> Any:
        return match_partition_rules(self.rules, params)


def partitioner_from_config(
    mesh: Mesh,
    param_partition: str = "replicated",
    model: str = "mlp",
) -> Partitioner:
    """CR/env -> partitioner: ``replicated`` (data parallel) or ``rules``
    (the family's stock rule table over fsdp/tp)."""
    if param_partition in ("replicated", "data"):
        return DataParallelPartitioner(mesh)
    if param_partition in ("rules", "spmd"):
        layout = SpecLayout()
        table = (seq_rules(layout) if model.startswith("seq")
                 else mlp_rules(layout))
        return SPMDPartitioner(mesh, table, layout=layout)
    raise ValueError(
        f"unknown param_partition {param_partition!r} "
        "(expected replicated|rules)")
