"""Device-mesh construction for sharded scoring and retraining.

The reference scales by Kafka partitions and k8s replicas (SURVEY.md §2,
"Parallelism strategies"); the TPU-native analog is a 2-D
``jax.sharding.Mesh`` over the pod:

- axis ``"data"`` — batch shards (data parallelism): each chip scores or
  trains on its slice of the micro-batch; gradient psum rides the ICI.
- axis ``"model"`` — hidden-dimension shards (tensor parallelism) for wide
  models; matmul partials reduce over ICI.

For the tabular CCFD models the data axis does nearly all the work
(BASELINE.json configs[4]: "SGD on TPU, pmap over v5e-4" — here expressed
as pjit over the data axis); the model axis exists so the same code drives
wide-MLP experiments and validates the collective layout.
"""

from __future__ import annotations

from jax.sharding import Mesh
import jax
import numpy as np

DATA_AXIS = "data"
MODEL_AXIS = "model"

# first-class partitioning-layer axis names (parallel/partition.py): the
# canonical data/fsdp/tp vocabulary the rule tables speak. ``MODEL_AXIS``
# stays as the legacy 2-D mesh's second axis name; the named mesh below is
# the serving platform's shape.
FSDP_AXIS = "fsdp"
TP_AXIS = "tp"
NAMED_AXES = (DATA_AXIS, FSDP_AXIS, TP_AXIS)


def make_mesh(
    devices: list | None = None, model_parallel: int = 1
) -> Mesh:
    """(n/model_parallel) x model_parallel mesh over the given devices."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if n % model_parallel != 0:
        raise ValueError(
            f"{n} devices not divisible by model_parallel={model_parallel}"
        )
    grid = np.asarray(devices).reshape(n // model_parallel, model_parallel)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def make_named_mesh(
    devices: list | None = None, fsdp: int = 1, tp: int = 1
) -> Mesh:
    """3-D ``(data, fsdp, tp)`` named mesh; data absorbs the remainder.

    The partitioning layer's canonical shape (parallel/partition.py):
    batches shard over ``data``, param rules speak ``fsdp``/``tp``. Axes
    an operator leaves at 1 cost nothing — a pure data-parallel serving
    mesh is ``(n, 1, 1)`` and every rule's fsdp/tp entry lands on a
    size-1 axis (replication)."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    fsdp, tp = max(1, int(fsdp)), max(1, int(tp))
    if n % (fsdp * tp) != 0:
        raise ValueError(
            f"{n} devices not divisible by fsdp*tp={fsdp * tp}"
        )
    grid = np.asarray(devices).reshape(n // (fsdp * tp), fsdp, tp)
    return Mesh(grid, NAMED_AXES)
