"""Model checkpointing (orbax-backed with a numpy fallback).

The reference has no model checkpointing — the classifier is trained
offline and baked into a container image (SURVEY.md §5, reference
deploy/model/modelfull.json:24). Online retraining makes checkpoints
necessary: the serving scorer must survive restarts with its latest
retrained weights, and retraining must resume from the last step.

Uses ``orbax.checkpoint`` when importable (the production path — async,
sharding-aware) and falls back to a plain ``.npz`` of the flattened pytree
otherwise, so checkpointing never becomes an install-time dependency.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any

import jax
import numpy as np


def _step_dirs(root: str) -> list[tuple[int, str]]:
    out = []
    if not os.path.isdir(root):
        return out
    for name in os.listdir(root):
        m = re.fullmatch(r"step_(\d+)", name)
        if m:
            out.append((int(m.group(1)), os.path.join(root, name)))
    return sorted(out)


class CheckpointManager:
    def __init__(self, root: str, keep: int = 3, use_orbax: bool | None = None):
        self.root = root
        self.keep = keep
        # steps retention must never delete (beyond the newest-``keep``
        # window): the model lifecycle pins its CHAMPION's checkpoint here
        # so a stream of rejected candidates can't GC the one checkpoint
        # rollback/restart restore from
        self.pinned: set[int] = set()
        os.makedirs(root, exist_ok=True)
        if use_orbax is None:
            try:
                import orbax.checkpoint  # noqa: F401

                use_orbax = True
            except ImportError:  # pragma: no cover
                use_orbax = False
        self.use_orbax = use_orbax

    # -- save -------------------------------------------------------------
    def save(self, step: int, params: Any) -> str:
        path = os.path.join(self.root, f"step_{step}")
        if self.use_orbax:
            import orbax.checkpoint as ocp

            ckptr = ocp.PyTreeCheckpointer()
            ckptr.save(os.path.abspath(path), jax.tree.map(np.asarray, params),
                       force=True)
        else:
            os.makedirs(path, exist_ok=True)
            leaves, treedef = jax.tree.flatten(params)
            np.savez(
                os.path.join(path, "params.npz"),
                **{f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)},
            )
            with open(os.path.join(path, "treedef.json"), "w") as f:
                json.dump({"n_leaves": len(leaves)}, f)
        self._gc()
        return path

    # -- restore ----------------------------------------------------------
    def latest_step(self) -> int | None:
        dirs = _step_dirs(self.root)
        return dirs[-1][0] if dirs else None

    def restore(self, like: Any, step: int | None = None) -> tuple[Any, int] | None:
        """Restore params structured like ``like``; returns (params, step)."""
        dirs = _step_dirs(self.root)
        if not dirs:
            return None
        if step is None:
            step, path = dirs[-1]
        else:
            match = [d for d in dirs if d[0] == step]
            if not match:
                raise FileNotFoundError(f"no checkpoint for step {step} in {self.root}")
            step, path = match[0]
        if self.use_orbax:
            import orbax.checkpoint as ocp

            ckptr = ocp.PyTreeCheckpointer()
            restored = ckptr.restore(os.path.abspath(path))
            # orbax returns plain nested containers; rebuild like's structure
            leaves = jax.tree.leaves(restored)
            treedef = jax.tree.structure(like)
            return jax.tree.unflatten(treedef, leaves), step
        data = np.load(os.path.join(path, "params.npz"))
        leaves = [data[f"leaf_{i}"] for i in range(len(data.files))]
        treedef = jax.tree.structure(like)
        return jax.tree.unflatten(treedef, leaves), step

    def _gc(self) -> None:
        dirs = _step_dirs(self.root)
        for step, path in dirs[: -self.keep] if self.keep else []:
            if step in self.pinned:
                continue
            import shutil

            shutil.rmtree(path, ignore_errors=True)
