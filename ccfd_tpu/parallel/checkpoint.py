"""Model checkpointing (orbax-backed with a numpy fallback).

The reference has no model checkpointing — the classifier is trained
offline and baked into a container image (SURVEY.md §5, reference
deploy/model/modelfull.json:24). Online retraining makes checkpoints
necessary: the serving scorer must survive restarts with its latest
retrained weights, and retraining must resume from the last step.

Uses ``orbax.checkpoint`` when importable (the production path — async,
sharding-aware) and falls back to a plain ``.npz`` of the flattened pytree
otherwise, so checkpointing never becomes an install-time dependency.

Integrity (runtime/durability.py): the npz path writes ``params.npz``
framed under a sha256 (atomic, fsynced); the orbax path — whose internal
files are not ours to frame — gets a checksum manifest over the step dir.
``restore`` VERIFIES before loading: a corrupt checkpoint is quarantined
(the step dir renamed ``*.corrupt``, so it leaves the step listing and is
never retried) and raises :class:`CorruptArtifactError`, and callers fall
back to :meth:`newest_verified_step` — the lifecycle controller walks the
pinned/parent steps and, when NOTHING verifies, pins serving to the rules
tier instead of publishing an unverified tree. Step dirs written before
this plane existed load as legacy (unverified, counted)."""

from __future__ import annotations

import io
import json
import os
import re
from typing import Any, Iterable

import jax
import numpy as np

from ccfd_tpu.runtime.durability import (
    CorruptArtifactError,
    note,
    read_artifact,
    sweep_tmp,
    verify_dir_manifest,
    verify_file,
    write_artifact,
    write_dir_manifest,
)


def _step_dirs(root: str) -> list[tuple[int, str]]:
    out = []
    if not os.path.isdir(root):
        return out
    for name in os.listdir(root):
        m = re.fullmatch(r"step_(\d+)", name)
        if m:
            out.append((int(m.group(1)), os.path.join(root, name)))
    return sorted(out)


class CheckpointManager:
    def __init__(self, root: str, keep: int = 3, use_orbax: bool | None = None):
        self.root = root
        self.keep = keep
        # steps retention must never delete (beyond the newest-``keep``
        # window): the model lifecycle pins its CHAMPION's checkpoint here
        # so a stream of rejected candidates can't GC the one checkpoint
        # rollback/restart restore from
        self.pinned: set[int] = set()
        os.makedirs(root, exist_ok=True)
        # a crash mid-save leaves orphan tmp debris in the step dirs
        sweep_tmp(root, *(p for _s, p in _step_dirs(root)))
        if use_orbax is None:
            try:
                import orbax.checkpoint  # noqa: F401

                use_orbax = True
            except ImportError:  # pragma: no cover
                use_orbax = False
        self.use_orbax = use_orbax

    # -- save -------------------------------------------------------------
    def save(self, step: int, params: Any) -> str:
        path = os.path.join(self.root, f"step_{step}")
        if self.use_orbax:
            import orbax.checkpoint as ocp

            ckptr = ocp.PyTreeCheckpointer()
            ckptr.save(os.path.abspath(path), jax.tree.map(np.asarray, params),
                       force=True)
            # integrity manifest over orbax's internal files: restore (and
            # verify_step) checks every file's sha256 against it
            write_dir_manifest(path, artifact="checkpoint")
        else:
            os.makedirs(path, exist_ok=True)
            leaves, treedef = jax.tree.flatten(params)
            buf = io.BytesIO()
            np.savez(
                buf,
                **{f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)},
            )
            # framed + fsynced + atomic; a failed write (full disk,
            # injected fault) keeps the previous state — restore-side
            # verification and the newest-verified fallback own recovery
            write_artifact(os.path.join(path, "params.npz"), buf.getvalue(),
                           artifact="checkpoint", retain=0)
            write_artifact(
                os.path.join(path, "treedef.json"),
                json.dumps({"n_leaves": len(leaves)}).encode(),
                artifact="checkpoint", retain=0)
        self._gc()
        return path

    # -- verification -----------------------------------------------------
    def verify_step(self, step: int) -> bool | None:
        """True when the step's checkpoint verifies (or predates the
        integrity plane — legacy, nothing to check against), False when
        it fails its checksum, None when no such step exists."""
        match = [d for d in _step_dirs(self.root) if d[0] == step]
        if not match:
            return None
        _step, path = match[0]
        npz = os.path.join(path, "params.npz")
        if os.path.exists(npz):
            return bool(verify_file(npz))
        return verify_dir_manifest(path, artifact="checkpoint") is not False

    def newest_verified_step(self, prefer: Iterable[int] = ()) -> int | None:
        """The first step that verifies, trying ``prefer`` in order first
        and then every step newest-first — the champion-restore fallback
        order (pinned/parent before arbitrary history)."""
        seen: set[int] = set()
        steps = [s for s, _p in _step_dirs(self.root)]
        for s in list(prefer) + sorted(steps, reverse=True):
            if s is None or s in seen or s not in steps:
                continue
            seen.add(s)
            if self.verify_step(s):
                return s
        return None

    def quarantine_step(self, step: int) -> str | None:
        """Move a corrupt step dir out of the listing (``*.corrupt``) so
        restart/rollback never re-reads it; returns the new path."""
        match = [d for d in _step_dirs(self.root) if d[0] == step]
        if not match:
            return None
        _step, path = match[0]
        dest = f"{path}.corrupt"
        try:
            # ccfd-lint: disable=durability-seam -- quarantine rename (the sanctioned exception): counted via note() below
            os.replace(path, dest)
        except OSError:
            return None
        note("corrupt", artifact="checkpoint")
        import logging

        logging.getLogger(__name__).error(
            "corrupt checkpoint step %d quarantined to %s", step, dest)
        return dest

    # -- restore ----------------------------------------------------------
    def latest_step(self) -> int | None:
        dirs = _step_dirs(self.root)
        return dirs[-1][0] if dirs else None

    def restore(self, like: Any, step: int | None = None,
                verify: bool = True) -> tuple[Any, int] | None:
        """Restore params structured like ``like``; returns (params, step).

        With ``verify`` (default), a checkpoint that fails its checksum —
        or whose bytes no longer load — is QUARANTINED and raises
        :class:`CorruptArtifactError`; callers fall back to
        :meth:`newest_verified_step` (the lifecycle controller's champion
        restore does) instead of serving corruption."""
        dirs = _step_dirs(self.root)
        if not dirs:
            return None
        if step is None:
            step, path = dirs[-1]
        else:
            match = [d for d in dirs if d[0] == step]
            if not match:
                raise FileNotFoundError(f"no checkpoint for step {step} in {self.root}")
            step, path = match[0]
        if self.use_orbax and not os.path.exists(
                os.path.join(path, "params.npz")):
            if verify and verify_dir_manifest(
                    path, artifact="checkpoint") is False:
                self.quarantine_step(step)
                raise CorruptArtifactError(
                    f"checkpoint step {step} failed manifest verification")
            import orbax.checkpoint as ocp

            ckptr = ocp.PyTreeCheckpointer()
            restored = ckptr.restore(os.path.abspath(path))
            # orbax returns plain nested containers; rebuild like's structure
            leaves = jax.tree.leaves(restored)
            treedef = jax.tree.structure(like)
            return jax.tree.unflatten(treedef, leaves), step
        import zipfile

        npz_path = os.path.join(path, "params.npz")
        try:
            raw = read_artifact(npz_path, artifact="checkpoint",
                                fallback=False, quarantine=False)
            data = np.load(io.BytesIO(raw))
            leaves = [data[f"leaf_{i}"] for i in range(len(data.files))]
        except (CorruptArtifactError, zipfile.BadZipFile, ValueError,
                KeyError) as e:
            # quarantine the WHOLE step dir (params + treedef move
            # together) so the step leaves the listing
            if verify:
                self.quarantine_step(step)
                raise CorruptArtifactError(
                    f"checkpoint step {step} unreadable: {e!r}") from e
            raise
        treedef = jax.tree.structure(like)
        return jax.tree.unflatten(treedef, leaves), step

    def _gc(self) -> None:
        dirs = _step_dirs(self.root)
        for step, path in dirs[: -self.keep] if self.keep else []:
            if step in self.pinned:
                continue
            import shutil

            shutil.rmtree(path, ignore_errors=True)
