"""Sharded training: the online-retrain capability (BASELINE.json configs[4]).

The reference never trains in-cluster — its model is trained offline and
baked into a container (SURVEY.md §5 "Checkpoint / resume"). The TPU build
upgrades this to first-class online retraining: SGD on process-engine
labels, pjit-sharded over the device mesh (data-parallel gradients psum
over ICI; optional tensor-parallel hidden dims), with the optimizer state
sharded like the params so nothing is replicated that doesn't have to be.

``make_train_step`` builds ONE jitted step covering forward + weighted-BCE
loss + backward + optax update, with explicit NamedShardings in/out and
donated state buffers — the whole step is a single XLA executable per batch
shape.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ccfd_tpu.models import mlp
from ccfd_tpu.parallel.mesh import DATA_AXIS
from ccfd_tpu.parallel.sharding import batch_spec, label_spec, mlp_param_spec


@dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 1e-3
    momentum: float = 0.9
    pos_weight: float = 8.0  # up-weight the rare fraud class
    compute_dtype: str = "bfloat16"


def make_optimizer(tc: TrainConfig) -> optax.GradientTransformation:
    return optax.sgd(tc.learning_rate, momentum=tc.momentum)


def init_state(params: Any, tc: TrainConfig) -> dict[str, Any]:
    return {
        "params": params,
        "opt_state": make_optimizer(tc).init(params),
        "step": jnp.zeros((), jnp.int32),
    }


def make_train_step(
    tc: TrainConfig,
    mesh: Mesh | None = None,
    loss_fn: Callable[..., jax.Array] | None = None,
    partitioner: Any = None,
) -> Callable[[dict, jax.Array, jax.Array], tuple[dict, jax.Array]]:
    """Jitted (state, x, y) -> (state, loss). With a ``partitioner``
    (parallel/partition.py) the step jits through its explicit-sharding
    entry point — batch over the data axis, params/opt-state per the
    partitioner's layout (replicated for pure dp, rule-table for SPMD),
    donated state. With a bare ``mesh``, the legacy hand-rolled
    mlp_param_spec layout. Without either, a plain single-device jit."""
    dtype = jnp.bfloat16 if tc.compute_dtype == "bfloat16" else jnp.float32
    base_loss = loss_fn or (
        lambda p, x, y: mlp.loss_fn(p, x, y, pos_weight=tc.pos_weight, compute_dtype=dtype)
    )
    optimizer = make_optimizer(tc)

    def step(state: dict, x: jax.Array, y: jax.Array) -> tuple[dict, jax.Array]:
        loss, grads = jax.value_and_grad(base_loss)(state["params"], x, y)
        updates, opt_state = optimizer.update(grads, state["opt_state"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        return {
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }, loss

    if partitioner is not None:
        compiled_p: dict[str, Callable] = {}

        def wrapped_p(state: dict, x: jax.Array, y: jax.Array):
            if "fn" not in compiled_p:
                compiled_p["fn"] = partitioner.partition_train_step(
                    step, state)
            return compiled_p["fn"](state, x, y)

        wrapped_p._compiled = compiled_p  # type: ignore[attr-defined]
        return wrapped_p

    if mesh is None:
        return jax.jit(step, donate_argnums=(0,))

    def state_shardings(state: dict) -> dict:
        pspec = mlp_param_spec(state["params"], mesh)
        return {
            "params": pspec,
            # optimizer state embeds param-shaped leaves (momentum traces):
            # shard those like their params, replicate scalars/counters
            "opt_state": _opt_spec_like(state["opt_state"], state["params"], pspec, mesh),
            "step": NamedSharding(mesh, P()),
        }

    compiled: dict[str, Callable] = {}

    def wrapped(state: dict, x: jax.Array, y: jax.Array):
        if "fn" not in compiled:
            shardings = state_shardings(state)
            compiled["fn"] = jax.jit(
                step,
                in_shardings=(shardings, batch_spec(mesh), label_spec(mesh)),
                out_shardings=(shardings, NamedSharding(mesh, P())),
                donate_argnums=(0,),
            )
        return compiled["fn"](state, x, y)

    # the inner jit is built on first call (shardings need a concrete
    # state tree); exposing the cache lets tests lower the REAL compiled
    # step and pin its HLO (e.g. the gradient all-reduce's presence)
    wrapped._compiled = compiled  # type: ignore[attr-defined]
    return wrapped


def _opt_spec_like(opt_state: Any, params: Any, pspec: Any, mesh: Mesh) -> Any:
    """Optax states embed param-*structured* subtrees (momentum traces);
    shard those exactly like the params, replicate everything else
    (step counters, scalars). Matching is structural, not by shape — two
    same-shaped params can have different shardings."""
    ptree = jax.tree.structure(params)
    rep = NamedSharding(mesh, P())

    def is_param_like(node: Any) -> bool:
        try:
            return jax.tree.structure(node) == ptree
        except TypeError:  # pragma: no cover - unhashable exotic nodes
            return False

    return jax.tree.map(
        lambda node: pspec if is_param_like(node) else rep,
        opt_state,
        is_leaf=is_param_like,
    )


# ---------------------------------------------------------------------------
# Convenience offline trainer (model prep for serving/bench)


def fit_mlp(
    X: np.ndarray,
    y: np.ndarray,
    hidden: int = mlp.DEFAULT_HIDDEN,
    steps: int = 500,
    batch: int = 1024,
    tc: TrainConfig | None = None,
    seed: int = 0,
    mesh: Mesh | None = None,
    balance_below: float = 0.05,
) -> Any:
    """Train the flagship MLP on (X, y); returns trained params.

    Heavily-imbalanced data (the real table runs 0.17% positive — a uniform
    1024-row batch carries ~1.7 frauds) trains with CLASS-BALANCED batches
    (25% positive) plus an exact log-odds recalibration of the output bias
    for the sampling ratio, so ranking quality comes from a strong gradient
    signal while ``proba_1`` stays calibrated to the true base rate (the
    FRAUD_THRESHOLD contract reads absolute probabilities). Kicks in
    whenever the positive rate is under ``balance_below`` (5%) — which
    includes the 1%-positive default synthetic stream, so demo and
    serve-``--train`` flows serve base-rate-calibrated probabilities now
    (previously their proba_1 ran ~pos_weight-inflated against
    FRAUD_THRESHOLD); datasets at or above 5% positives train as before.
    """
    tc = tc or TrainConfig()
    key = jax.random.PRNGKey(seed)
    params = mlp.init(key, num_features=X.shape[1], hidden=hidden)
    params = mlp.set_normalizer(params, X.mean(0), X.std(0))
    state = init_state(params, tc)
    step_fn = make_train_step(tc, mesh=mesh)
    rng = np.random.default_rng(seed)
    n = X.shape[0]
    bsz = min(batch, n)
    pos_idx = np.flatnonzero(y == 1)
    p_true = len(pos_idx) / max(1, n)
    balanced = 0 < p_true < balance_below and len(pos_idx) >= 2
    q = 0.25  # positive fraction per balanced batch
    n_pos_b = max(1, int(bsz * q))
    neg_idx = np.flatnonzero(y == 0) if balanced else None
    for _ in range(steps):
        if balanced:
            idx = np.concatenate([
                rng.choice(pos_idx, size=n_pos_b, replace=True),
                rng.choice(neg_idx, size=bsz - n_pos_b, replace=True),
            ])
        else:
            idx = rng.integers(0, n, size=bsz)
        state, _ = step_fn(
            state, jnp.asarray(X[idx], jnp.float32), jnp.asarray(y[idx], jnp.float32)
        )
    params = jax.tree.map(lambda a: a, state["params"])  # detach from donation
    if balanced:
        # exact prior correction for logistic models trained at sampling
        # rate q but deployed at base rate p: shift the output logit by
        # -[logit(q) - logit(p)] (King & Zeng 2001 rare-events correction).
        # The loss's pos_weight multiplies positive-class odds the same
        # multiplicative way, so it folds into the same offset — without
        # the log(w) term, proba_1 would serve ~w-times-inflated odds
        # against the FRAUD_THRESHOLD absolute-probability contract.
        q_eff = n_pos_b / bsz
        off = float(
            np.log(max(1e-9, tc.pos_weight))
            + np.log(q_eff / (1 - q_eff))
            - np.log(p_true / (1 - p_true))
        )
        layers = list(params["layers"])
        last = dict(layers[-1])
        last["b"] = last["b"] - off
        layers[-1] = last
        params = dict(params)
        params["layers"] = layers
    return params
